"""Bulk object-transfer plane: raw-frame chunk streams + pull admission.

TPU-native analog of the reference object manager's transfer machinery
(ref: src/ray/object_manager/object_manager.h:119 chunked transfer,
pull_manager.h:57 prioritized pulls with byte budgets, push_manager.h:32
per-peer in-flight chunk caps). Re-designed rather than translated:

 * The control RPC plane frames every payload through msgpack — fine for
   leases, ruinous for gigabyte objects (each 8 MiB chunk pays ~8 full
   copies through pack/concat/unpack). This plane speaks a raw protocol
   on its own listener: a tiny header, then the chunk bytes written
   straight from the holder's sealed mmap (``sock_sendall(view)``) and
   received straight into the puller's store allocation
   (``sock_recv_into(buf)``) — two copies end to end.
 * Each pull fans its byte range over several connections ("streams"),
   so round trips overlap and a single TCP window never bounds a DCN
   link. Streams that die mid-pull are retried on a fresh connection;
   the pull fails over to the control-RPC path only when the whole
   plane is unreachable.
 * PullManager admission-controls restores and rebalances: bytes in
   flight are capped (``object_transfer_max_inflight_bytes``) and
   queued pulls run highest-priority-first, FIFO within a class —
   task-argument pulls (a worker is blocked on them) outrank
   prefetches.
"""

from __future__ import annotations

import asyncio
import os
import socket
import struct
import time
from typing import Callable, Dict, List, Optional, Tuple

from .ids import ObjectID
from ..util.tracing import record_lane_event

_REQ_LEN = struct.Struct("<I")
_RESP = struct.Struct("<QQ")   # (total object size, this payload length)
_ABSENT = (1 << 64) - 1
# per-chunk I/O deadline: generous for a saturated DCN link moving one
# chunk, but bounded — an unbounded read against a half-open peer would
# wedge the pull AND its PullManager byte reservation forever
_IO_TIMEOUT_S = 60.0
# cut-through relay: how long a range request on an in-progress object
# may block for the watermark to pass it before reporting absent. Must
# stay below _IO_TIMEOUT_S or a stalled upstream would trip the CHILD's
# transport deadline (a retry storm) instead of a clean absent-fallback.
_RELAY_WAIT_S = 45.0


def _parse_addr(address: str):
    if "/" in address or address.startswith("@"):
        return ("unix", address)
    host, _, port = address.rpartition(":")
    return ("tcp", host, int(port))


async def _recv_exactly(loop, sock, n: int) -> bytes:
    parts = []
    remaining = n
    while remaining:
        chunk = await loop.sock_recv(sock, remaining)
        if not chunk:
            raise ConnectionError("transfer peer closed mid-frame")
        parts.append(chunk)
        remaining -= len(chunk)
    return b"".join(parts)


async def _recv_into_exactly(loop, sock, view) -> None:
    got = 0
    while got < len(view):
        n = await loop.sock_recv_into(sock, view[got:])
        if n == 0:
            raise ConnectionError("transfer peer closed mid-chunk")
        got += n


class TransferServer:
    """Serves ranges of sealed local objects over the raw protocol.

    Request:  [u32 len][msgpack {"oid": bytes, "offset": u64, "len": u64,
                                 "puller": hex (optional)}]
    Response: [u64 total_size][u64 payload_len][payload bytes]
              total_size == 2**64-1 -> object not present here.
    One request at a time per connection; pullers parallelize by opening
    several connections (ref: push_manager.h chunking — the unit of
    interleaving is the chunk, here the connection).

    A request that names its puller ties that (object, puller) pair to
    the data-plane connections carrying it: when the LAST such
    connection closes, `on_puller_gone(oid, puller)` fires. The raylet
    uses this to expire the puller's sender-slot grant the moment its
    transfer ends (or its process dies mid-pull) instead of pinning one
    of the capped slots until the 120 s TTL sweep — the control-RPC
    release can be lost exactly when the puller crashes."""

    def __init__(self, store, address_hint: str,
                 advertise_host: Optional[str] = None,
                 on_puller_gone: Optional[Callable] = None):
        self.store = store
        self._hint = address_hint
        self._advertise_host = advertise_host
        self._on_puller_gone = on_puller_gone
        # (oid bytes, puller hex) -> count of open data conns claiming it
        self._puller_conns: Dict[Tuple[bytes, str], int] = {}
        self._listener: Optional[socket.socket] = None
        self._accept_task: Optional[asyncio.Task] = None
        self._conn_tasks: set = set()
        self.address = ""

    async def start(self) -> str:
        kind = _parse_addr(self._hint)
        if kind[0] == "unix":
            path = kind[1]
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.bind(path)
            self.address = path
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((kind[1], kind[2]))
            host = self._advertise_host or kind[1] or "127.0.0.1"
            self.address = f"{host}:{sock.getsockname()[1]}"
        sock.listen(64)
        sock.setblocking(False)
        self._listener = sock
        self._accept_task = asyncio.ensure_future(self._accept_loop())
        return self.address

    async def stop(self) -> None:
        if self._accept_task is not None:
            self._accept_task.cancel()
        if self._listener is not None:
            self._listener.close()
        for task in list(self._conn_tasks):
            task.cancel()
        if self.address and "/" in self.address:
            try:
                os.unlink(self.address)
            except OSError:
                pass

    async def _accept_loop(self):
        loop = asyncio.get_event_loop()
        while True:
            try:
                conn, _ = await loop.sock_accept(self._listener)
            except asyncio.CancelledError:
                raise  # teardown cancel: keep the accept task CANCELLED
            except OSError:
                return  # listener closed under us: clean exit
            conn.setblocking(False)
            task = asyncio.ensure_future(self._serve(conn))
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)

    async def _serve(self, conn: socket.socket):
        from . import wire

        loop = asyncio.get_event_loop()
        claimed: set = set()   # (oid bytes, puller hex) seen on THIS conn
        try:
            while True:
                try:
                    header = await _recv_exactly(loop, conn, _REQ_LEN.size)
                except ConnectionError:
                    return
                (req_len,) = _REQ_LEN.unpack(header)
                if req_len > 1 << 16:
                    return  # malformed
                req = wire._unpack(await _recv_exactly(loop, conn, req_len))
                oid = ObjectID(req["oid"])
                puller = req.get("puller")
                if puller and self._on_puller_gone is not None:
                    key = (req["oid"], puller)
                    if key not in claimed:
                        claimed.add(key)
                        self._puller_conns[key] = (
                            self._puller_conns.get(key, 0) + 1)
                view = self.store.get(oid)
                if view is None:
                    if await self._serve_inprogress(loop, conn, oid, req):
                        continue
                    # the creation may have sealed (registry cleared)
                    # between the miss and the in-progress check
                    view = self.store.get(oid)
                    if view is None:
                        await loop.sock_sendall(conn,
                                                _RESP.pack(_ABSENT, 0))
                        continue
                total = len(view)
                offset = min(req["offset"], total)
                length = min(req["len"], total - offset)
                await loop.sock_sendall(
                    conn, _RESP.pack(total, length))
                if length:
                    # straight from the sealed mmap to the kernel
                    await loop.sock_sendall(
                        conn, view[offset:offset + length])
        except asyncio.CancelledError:
            raise  # serve task cancelled at close: finally still closes conn
        except (ConnectionError, OSError):
            pass  # peer went away mid-serve: its puller retries elsewhere
        finally:
            conn.close()
            for key in claimed:
                left = self._puller_conns.get(key, 0) - 1
                if left > 0:
                    self._puller_conns[key] = left
                    continue
                self._puller_conns.pop(key, None)
                try:
                    self._on_puller_gone(ObjectID(key[0]), key[1])
                except Exception:  # graftlint: ignore[swallow] — grant
                    pass  # expiry is best-effort; the TTL still backstops

    async def _serve_inprogress(self, loop, conn, oid: ObjectID,
                                req) -> bool:
        """Cut-through relay: serve a range of an object this node is
        still RECEIVING (or restoring from spill). The request blocks
        until the creation's contiguous watermark passes the range, then
        sends straight from the unsealed mapping — an interior
        broadcast-tree node forwards chunks as they arrive, so tree
        depth adds only pipeline fill, not whole-object store-and-
        forward hops. A failed/stalled upstream answers absent, failing
        children fast onto another holder. Returns False when no
        in-progress creation exists (caller answers absent)."""
        getter = getattr(self.store, "inprogress", None)
        entry = getter(oid) if getter is not None else None
        if entry is None:
            return False
        total = entry.size
        offset = min(req["offset"], total)
        length = min(req["len"], total - offset)
        if length and not await entry.wait_for(offset + length,
                                               _RELAY_WAIT_S):
            await loop.sock_sendall(conn, _RESP.pack(_ABSENT, 0))
            return True
        await loop.sock_sendall(conn, _RESP.pack(total, length))
        if length:
            await loop.sock_sendall(conn,
                                    entry.buf[offset:offset + length])
        return True


class _Stream:
    """One connection to a peer transfer server. `puller` (the pulling
    node's hex id) rides every request so the holder can tie its
    sender-slot grant to this connection's lifetime."""

    def __init__(self, address: str, puller: Optional[str] = None):
        self.address = address
        self.puller = puller
        self.sock: Optional[socket.socket] = None

    async def connect(self, timeout: float = 10.0) -> None:
        loop = asyncio.get_event_loop()
        kind = _parse_addr(self.address)
        if kind[0] == "unix":
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            target = kind[1]
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            target = (kind[1], kind[2])
        sock.setblocking(False)
        await asyncio.wait_for(loop.sock_connect(sock, target), timeout)
        self.sock = sock

    async def fetch_range(self, oid: ObjectID, offset: int, length: int,
                          out_view) -> Tuple[int, int]:
        """Fetch [offset, offset+length) into out_view (len >= length).
        Returns (total_object_size, bytes_received); total == -1 when the
        holder no longer has the object."""
        from . import wire

        loop = asyncio.get_event_loop()
        body = {"oid": oid.binary(), "offset": offset, "len": length}
        if self.puller:
            body["puller"] = self.puller
        req = wire._pack(body)
        await loop.sock_sendall(self.sock,
                                _REQ_LEN.pack(len(req)) + req)
        header = await _recv_exactly(loop, self.sock, _RESP.size)
        total, payload_len = _RESP.unpack(header)
        if total == _ABSENT:
            return -1, 0
        if payload_len:
            # straight from the kernel into the store allocation
            await _recv_into_exactly(loop, self.sock,
                                     out_view[:payload_len])
        return total, payload_len

    def close(self):
        if self.sock is not None:
            self.sock.close()
            self.sock = None


async def fetch_object(address: str, oid: ObjectID, create_buf,
                       *, streams: int, chunk_bytes: int,
                       seal: Callable, abort: Callable,
                       admit_bytes=None, on_progress=None,
                       puller: Optional[str] = None) -> Optional[int]:
    """Pull one object from `address` with up to `streams` parallel
    connections. `create_buf(size) -> memoryview` allocates the
    destination once the size is known; `admit_bytes(size)` (async,
    optional) runs first — the PullManager's byte-budget gate.
    `on_progress(watermark)` (optional) fires as the CONTIGUOUS received
    prefix grows — the cut-through watermark a relaying node publishes
    so its own pullers can stream behind this pull. `puller` (this
    node's hex id) is stamped on every request so the holder can expire
    this pull's sender-slot grant when the connections close. Returns
    the object size, or None when the holder no longer has it. Raises on
    transport failure (the caller owns retry/fallback policy)."""
    pull_t0 = time.time()
    first = _Stream(address, puller)
    await first.connect()
    buf = None
    opened: List[_Stream] = [first]
    tasks: List[asyncio.Task] = []
    try:
        # chunk 0 doubles as the size probe
        probe = bytearray(chunk_bytes)
        total, got = await asyncio.wait_for(
            first.fetch_range(oid, 0, chunk_bytes, memoryview(probe)),
            _IO_TIMEOUT_S)
        if total < 0:
            return None
        if admit_bytes is not None:
            await admit_bytes(total)
        buf = create_buf(total)
        buf[:got] = probe[:got]
        del probe
        if on_progress is not None:
            on_progress(got)
        if got >= total:
            buf.release()
            buf = None
            seal()
            record_lane_event("transfer", f"pull {oid.hex()[:12]}",
                              pull_t0, time.time(),
                              bytes=total, source=address)
            return total
        # fan the remaining range over parallel streams: stream i takes
        # chunks i, i+K, i+2K... — ranges interleave so every stream
        # finishes at roughly the same time regardless of link skew
        offsets = list(range(got, total, chunk_bytes))
        n_streams = max(1, min(streams, len(offsets)))
        next_i = 0
        # contiguous-prefix tracking for the relay watermark: chunk i is
        # "done" once its bytes sit in buf; the frontier is the first
        # incomplete chunk (single event loop — no lock needed)
        done_chunks = bytearray(len(offsets))
        frontier = 0

        def _chunk_done(i: int) -> None:
            nonlocal frontier
            done_chunks[i] = 1
            while frontier < len(offsets) and done_chunks[frontier]:
                frontier += 1
            if on_progress is not None:
                on_progress(total if frontier >= len(offsets)
                            else offsets[frontier])

        async def run_stream(stream: Optional[_Stream]):
            nonlocal next_i
            if stream is None:
                stream = _Stream(address, puller)
                await asyncio.wait_for(stream.connect(), _IO_TIMEOUT_S)
                opened.append(stream)
            retries = 0
            while True:
                i = next_i
                if i >= len(offsets):
                    return
                next_i = i + 1
                off = offsets[i]
                length = min(chunk_bytes, total - off)
                while True:
                    try:
                        # per-chunk deadline: a half-open holder (no
                        # FIN/RST) must not hang the pull — a wedged pull
                        # never releases its byte-budget reservation
                        t, n = await asyncio.wait_for(
                            stream.fetch_range(oid, off, length,
                                               buf[off:off + length]),
                            _IO_TIMEOUT_S)
                    except (ConnectionError, OSError,
                            asyncio.TimeoutError):
                        # one dropped stream must not demote a mostly-
                        # done pull to the control-RPC path: retry this
                        # chunk on a FRESH connection; only a holder
                        # that refuses reconnection fails the pull
                        stream.close()
                        retries += 1
                        if retries > 2:
                            raise
                        stream = _Stream(address, puller)
                        await asyncio.wait_for(stream.connect(),
                                               _IO_TIMEOUT_S)
                        opened.append(stream)
                        continue
                    if t < 0 or n < length:
                        raise ConnectionError(
                            "holder dropped object mid-transfer")
                    retries = 0
                    _chunk_done(i)
                    break

        tasks = [asyncio.ensure_future(run_stream(first))]
        tasks += [asyncio.ensure_future(run_stream(None))
                  for _ in range(n_streams - 1)]
        await asyncio.gather(*tasks)
        buf.release()
        buf = None
        seal()
        record_lane_event("transfer", f"pull {oid.hex()[:12]}",
                          pull_t0, time.time(),
                          bytes=total, source=address, streams=n_streams)
        return total
    except BaseException:
        # sibling streams must stop WRITING and drop their buffer views
        # before abort() — the store closes the mmap, which raises
        # BufferError (and leaks the tmp file) while views are exported
        for task in tasks:
            task.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        if buf is not None:
            buf.release()
            buf = None
            abort()
        raise
    finally:
        for stream in opened:
            stream.close()


class PullManager:
    """Admission control + prioritization for inbound pulls (ref:
    pull_manager.h:57 — bytes-in-flight budget, priority classes,
    retry-while-waiters).

    Two gates, both real:
      * concurrency — at most `max_concurrent` pulls run at once,
        admitted highest-priority-first, FIFO within a class;
      * bytes — a pull reserves its size (`acquire_bytes`) the moment
        the first chunk reveals it, BEFORE the store allocation; the
        reservation is released when the pull ends. Sizes are facts
        learned on the wire, never hints, so the ledger cannot drift."""

    PRIO_TASK_ARG = 0      # a lease/worker is blocked on this object
    PRIO_FETCH = 1         # explicit ray.get / wait fetches
    PRIO_BACKGROUND = 2    # prefetch/rebalance

    def __init__(self, max_inflight_bytes: int, start_pull,
                 max_concurrent: int = 8):
        self._budget = max_inflight_bytes
        self._max_concurrent = max_concurrent
        self._inflight_bytes = 0
        self._reserved: Dict[ObjectID, int] = {}
        self._byte_waiters: List[asyncio.Future] = []
        self._start_pull = start_pull     # async (oid) -> size|None
        self._queue: List[List] = []      # [prio, seq, oid]
        self._seq = 0
        self._active: Dict[ObjectID, asyncio.Task] = {}

    def request(self, oid: ObjectID, prio: int = 1,
                size_hint: int = 0) -> None:
        if oid in self._active:
            return
        for entry in self._queue:
            if entry[2] == oid:
                # priority upgrade: a worker newly blocked on a queued
                # fetch must jump it to the task-arg class
                if prio < entry[0]:
                    entry[0] = prio
                    self._pump()
                return
        self._seq += 1
        self._queue.append([prio, self._seq, oid])
        self._pump()

    def cancel(self, oid: ObjectID) -> None:
        self._queue = [e for e in self._queue if e[2] != oid]
        task = self._active.get(oid)
        if task is not None:
            task.cancel()

    @property
    def inflight(self) -> int:
        return len(self._active)

    async def acquire_bytes(self, oid: ObjectID, nbytes: int) -> None:
        """Reserve budget for a size just learned from the holder. The
        sole in-flight pull always admits (a single over-budget object
        must not wedge), otherwise waits for reservations to release."""
        while self._reserved and self._inflight_bytes + nbytes > self._budget:
            fut = asyncio.get_event_loop().create_future()
            self._byte_waiters.append(fut)
            await fut
        self._inflight_bytes += nbytes
        self._reserved[oid] = self._reserved.get(oid, 0) + nbytes

    def release_bytes(self, oid: ObjectID) -> None:
        nbytes = self._reserved.pop(oid, 0)
        self._inflight_bytes -= nbytes
        if nbytes:
            for fut in self._byte_waiters:
                if not fut.done():
                    fut.set_result(None)
            self._byte_waiters = []

    def _pump(self) -> None:
        while self._queue and len(self._active) < self._max_concurrent:
            self._queue.sort()
            prio, seq, oid = self._queue.pop(0)
            task = asyncio.ensure_future(self._run(oid))
            self._active[oid] = task

    async def _run(self, oid: ObjectID) -> None:
        try:
            await self._start_pull(oid)
        except asyncio.CancelledError:
            raise  # pull cancelled (release/shutdown): finally cleans up
        except Exception:
            pass  # pull failure is re-queued/surfaced by the directory
        finally:
            self.release_bytes(oid)  # safety net if the pull leaked one
            self._active.pop(oid, None)
            self._pump()
