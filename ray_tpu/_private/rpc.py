"""Control-plane RPC: asyncio message streams over unix or TCP sockets.

TPU-native analog of the reference rpc layer (ref: src/ray/rpc/grpc_server.h:88,
grpc_client.h:96, client_call.h:193, retryable_grpc_client.h). The control
plane stays host-side and socket-based (gRPC-over-DCN equivalent); the device
data plane never touches this layer — tensors move inside XLA programs.

Addresses: a path ("/tmp/.../x.sock") binds a unix-domain socket (intra-host);
"host:port" or "tcp://host:port" binds TCP (the DCN cross-host transport).
Binding port 0 picks a free port; the server's resolved address is
``server.address`` after ``start()``.

Wire format: [u32 frame_len][msgpack envelope] — the envelope layout and
every framework message struct live in ray_tpu/_private/wire.py (the N16
schema surface; ref: src/ray/protobuf/). A Frame is
(msg_id, kind, method, payload) with kind in {REQUEST, REPLY, ERROR, PUSH}.
PUSH frames implement server->client pubsub (ref: src/ray/pubsub) without a
pending long-poll.

Includes deterministic fault injection (ref: src/ray/rpc/rpc_chaos.h:23
`enum RpcFailure {Request, Response}`) driven by the
`testing_rpc_failure` config flag: "method=max_failures:req_prob:resp_prob".
"""

from __future__ import annotations

import asyncio
import itertools
import random
import struct
import sys
import threading
from typing import Any, Awaitable, Callable, Dict, Optional

from . import failpoints, wire
from .config import global_config

_LEN = struct.Struct("<I")

REQUEST, REPLY, ERROR, PUSH = 0, 1, 2, 3

_MAX_FRAME = 1 << 31


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


def parse_address(address: str):
    """("unix", path) | ("tcp", host, port)."""
    if address.startswith("tcp://"):
        address = address[len("tcp://"):]
    if "/" in address or ":" not in address:
        return ("unix", address)
    host, _, port = address.rpartition(":")
    return ("tcp", host or "127.0.0.1", int(port))


# The event loop keeps only WEAK references to tasks: a bare
# ``asyncio.ensure_future(coro())`` statement can be garbage-collected
# mid-await (observed here as spurious GeneratorExit under GC pressure),
# and its exception is never retrieved. Every fire-and-forget spawn in
# the control plane goes through background(), which pins the task until
# it finishes and drains the exception so the loop never logs
# "exception was never retrieved" at interpreter teardown.
_BACKGROUND_TASKS: set = set()


def background(coro) -> "asyncio.Future":
    """Spawn ``coro`` on the running loop, retaining a strong reference
    until completion; exceptions are retrieved (and dropped) on done."""
    task = asyncio.ensure_future(coro)
    _BACKGROUND_TASKS.add(task)

    def _done(t):
        _BACKGROUND_TASKS.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None and not isinstance(
                exc, (ConnectionError, ConnectionLost, OSError)):
            print(f"[rpc] background task failed: {exc!r}", file=sys.stderr)

    task.add_done_callback(_done)
    return task


class _ChaosInjector:
    """Deterministic-ish request/response dropping for fault-tolerance tests."""

    def __init__(self, spec: str):
        self.rules: Dict[str, list] = {}
        self._rng = random.Random(12345)
        if spec:
            for entry in spec.split(","):
                method, params = entry.split("=")
                parts = params.split(":")
                max_failures = int(parts[0])
                req_p = float(parts[1]) if len(parts) > 1 else 0.5
                resp_p = float(parts[2]) if len(parts) > 2 else 0.0
                self.rules[method] = [max_failures, req_p, resp_p]

    def should_drop_request(self, method: str) -> bool:
        rule = self.rules.get(method) or self.rules.get("*")
        if not rule or rule[0] <= 0:
            return False
        if self._rng.random() < rule[1]:
            rule[0] -= 1
            return True
        return False

    def should_drop_response(self, method: str) -> bool:
        rule = self.rules.get(method) or self.rules.get("*")
        if not rule or rule[0] <= 0:
            return False
        if self._rng.random() < rule[2]:
            rule[0] -= 1
            return True
        return False


def _frame(msg_id: int, kind: int, method: str, payload: Any) -> bytes:
    body = wire.encode_frame(msg_id, kind, method, payload)
    return _LEN.pack(len(body)) + body


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return wire.decode_frame(body)


Handler = Callable[[Any, "ServerConnection"], Awaitable[Any]]


class ServerConnection:
    """One accepted client connection; supports push back to the client."""

    def __init__(self, server: "RpcServer", reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.closed = asyncio.Event()
        self._write_lock = asyncio.Lock()
        self.peer_id: Optional[str] = None  # set by registration handlers

    async def push(self, method: str, payload: Any) -> None:
        try:
            async with self._write_lock:
                self.writer.write(_frame(0, PUSH, method, payload))
                await self.writer.drain()
        except (ConnectionError, RuntimeError):
            self.closed.set()

    async def _reply(self, msg_id: int, kind: int, method: str, payload: Any):
        async with self._write_lock:
            self.writer.write(_frame(msg_id, kind, method, payload))
            await self.writer.drain()


class RpcServer:
    """Unix-or-TCP RPC server dispatching to registered async handlers."""

    def __init__(self, address: str, name: str = "server",
                 advertise_host: Optional[str] = None):
        """``advertise_host``: for TCP binds on 0.0.0.0, the routable IP
        peers should dial (advertised in ``self.address`` after start)."""
        self.address = address
        self.advertise_host = advertise_host
        self.name = name
        self.handlers: Dict[str, Handler] = {}
        self.connections: set[ServerConnection] = set()
        self.on_disconnect: Optional[Callable[[ServerConnection], Awaitable[None]]] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._chaos = _ChaosInjector(global_config().testing_rpc_failure)

    # back-compat alias
    @property
    def socket_path(self) -> str:
        return self.address

    def register(self, method: str, handler: Handler) -> None:
        self.handlers[method] = handler

    def register_all(self, obj: Any, prefix: str = "handle_") -> None:
        for attr in dir(obj):
            if attr.startswith(prefix):
                self.register(attr[len(prefix):], getattr(obj, attr))

    async def start(self) -> None:
        kind = parse_address(self.address)
        if kind[0] == "unix":
            self._server = await asyncio.start_unix_server(
                self._on_client, path=kind[1])
        else:
            _, host, port = kind
            self._server = await asyncio.start_server(self._on_client, host, port)
            actual = self._server.sockets[0].getsockname()
            adv = self.advertise_host or ("127.0.0.1" if host == "0.0.0.0" else host)
            self.address = f"{adv}:{actual[1]}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # close live connections BEFORE wait_closed: since 3.12 wait_closed
        # blocks until every handler finishes, and handlers block on reads
        for conn in list(self.connections):
            try:
                conn.writer.close()
            except Exception:
                pass
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2)
            except Exception:
                pass

    async def _on_client(self, reader, writer):
        conn = ServerConnection(self, reader, writer)
        self.connections.add(conn)
        try:
            while True:
                msg_id, kind, method, payload = await _read_frame(reader)
                if kind != REQUEST:
                    continue
                if self._chaos.should_drop_request(method):
                    continue  # simulate lost request
                background(self._dispatch(conn, msg_id, method, payload))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.connections.discard(conn)
            conn.closed.set()
            if self.on_disconnect is not None:
                await self.on_disconnect(conn)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, conn, msg_id, method, payload):
        handler = self.handlers.get(method)
        try:
            # inside the try: a raise-armed failpoint rides the ERROR
            # reply to the caller — surfaced and attributed, not a hang
            if await failpoints.afire("rpc.server.dispatch",
                                      detail=method) == "drop":
                return  # injected lost request: never dispatched, no reply
            if handler is None:
                raise RpcError(f"{self.name}: no handler for '{method}'")
            result = await handler(payload, conn)
            if self._chaos.should_drop_response(method):
                return  # simulate lost reply
            await conn._reply(msg_id, REPLY, method, result)
        except asyncio.CancelledError:
            raise
        except BaseException as e:  # noqa: BLE001 — errors cross the wire
            try:
                await conn._reply(msg_id, ERROR, method, e)
            except Exception:
                pass


class RpcClient:
    """Client with automatic request/future matching and push subscriptions."""

    def __init__(self, address: str):
        self.address = address
        self.socket_path = address  # back-compat alias
        self._reader = None
        self._writer = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._msg_ids = itertools.count(1)
        self._push_handlers: Dict[str, Callable[[Any], Any]] = {}
        self._write_lock = asyncio.Lock()
        self._connect_lock = asyncio.Lock()
        self._recv_task: Optional[asyncio.Task] = None
        self.closed = False
        self._ever_connected = False
        # async callbacks fired after a RE-connect (transport came back,
        # e.g. a restarted GCS): server-side per-connection state —
        # pubsub subscriptions above all — must be re-established by the
        # client (ref: gcs_redis_failure_detector.h + the reference's
        # client-side resubscribe on GCS restart)
        self.on_reconnect: list = []
        # sync callback fired when the transport drops (recv loop exit),
        # clean or abrupt — a worker uses this to die with its raylet
        self.on_close: Optional[Callable[[], None]] = None

    def on_push(self, method: str, handler: Callable[[Any], Any]) -> None:
        self._push_handlers[method] = handler

    async def connect(self, timeout: float = 30.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        kind = parse_address(self.address)
        while True:
            try:
                if kind[0] == "unix":
                    self._reader, self._writer = await asyncio.open_unix_connection(kind[1])
                else:
                    self._reader, self._writer = await asyncio.open_connection(kind[1], kind[2])
                break
            except (ConnectionError, FileNotFoundError, OSError) as e:
                if asyncio.get_event_loop().time() > deadline:
                    raise ConnectionLost(
                        f"cannot connect to {self.address}") from e
                await asyncio.sleep(0.05)
        self.closed = False
        # a reconnect must not leave the previous loop reading the stream —
        # two readers on one StreamReader is a runtime error
        if self._recv_task is not None and not self._recv_task.done():
            self._recv_task.cancel()
        self._recv_task = asyncio.ensure_future(self._recv_loop())
        if self._ever_connected:
            for cb in list(self.on_reconnect):
                background(cb())
        self._ever_connected = True

    async def _recv_loop(self):
        try:
            while True:
                msg_id, kind, method, payload = await _read_frame(self._reader)
                if kind == PUSH:
                    handler = self._push_handlers.get(method)
                    if handler is not None:
                        res = handler(payload)
                        if asyncio.iscoroutine(res):
                            background(res)
                    continue
                fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    continue
                if kind == ERROR:
                    fut.set_exception(payload if isinstance(payload, BaseException)
                                      else RpcError(str(payload)))
                else:
                    fut.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.closed = True
            if self.on_close is not None:
                try:
                    self.on_close()
                except Exception:
                    pass
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(self.socket_path))
                    # mark retrieved: fire-and-forget callers dropping the
                    # future at shutdown must not spam "exception was
                    # never retrieved" (real awaiters still see it raise)
                    fut.exception()
            self._pending.clear()

    async def call(self, method: str, payload: Any = None, timeout: Optional[float] = None):
        if self.closed:
            raise ConnectionLost(self.socket_path)
        # before the pending-future registration so a raise-armed site
        # can't leak an entry; "drop" skips the write below and lets the
        # caller's timeout/retry machinery see a lost frame
        injected = await failpoints.afire("rpc.client.send", detail=method)
        msg_id = next(self._msg_ids)
        fut = asyncio.get_event_loop().create_future()
        self._pending[msg_id] = fut
        if injected != "drop":
            try:
                async with self._write_lock:
                    self._writer.write(_frame(msg_id, REQUEST, method, payload))
                    await self._writer.drain()
            except (ConnectionError, RuntimeError, OSError) as e:
                # a dead transport surfaces as ConnectionLost so retrying
                # callers reconnect instead of crashing on the raw OS error
                self._pending.pop(msg_id, None)
                self.closed = True
                raise ConnectionLost(f"{self.socket_path}: {e}") from e
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def call_retrying(self, method: str, payload: Any = None, *,
                            attempts: int = 5, base_delay: float = 0.05,
                            per_try_timeout: float = 10.0):
        """Retryable call (ref: retryable_grpc_client.h) — safe only for
        idempotent methods."""
        last: Exception | None = None
        for i in range(attempts):
            try:
                return await self.call(method, payload, timeout=per_try_timeout)
            except (asyncio.TimeoutError, ConnectionLost) as e:
                last = e
                # serialize reconnects: concurrent retriers racing connect()
                # would spawn duplicate recv loops on one stream
                async with self._connect_lock:
                    if self.closed:
                        try:
                            await self.connect(timeout=per_try_timeout)
                        except ConnectionLost:
                            pass
                await asyncio.sleep(base_delay * (2 ** i))
        raise last  # type: ignore[misc]

    async def close(self) -> None:
        self.closed = True
        task, self._recv_task = self._recv_task, None
        if task is not None and task is not asyncio.current_task():
            task.cancel()
            try:
                # awaiting a task we just cancelled: absorbing its
                # CancelledError IS the await's purpose here
                await task
            except BaseException:  # graftlint: ignore[swallow]
                pass
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class EventLoopThread:
    """Dedicated asyncio loop on a daemon thread — the instrumented-io-context
    analog (ref: src/ray/common/asio/). Sync code submits coroutines and
    blocks on concurrent futures."""

    def __init__(self, name: str = "ray_tpu_io"):
        self.loop = asyncio.new_event_loop()
        self._stopping = False
        self._spawned: set = set()   # strong refs to in-flight spawns
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        if threading.current_thread() is self.thread:
            # blocking on our own loop can never complete — this happens
            # when a destructor runs during GC *inside* the loop thread
            # and calls a sync API; raise so the caller can degrade to
            # spawn() instead of wedging the whole loop forever
            coro.close()
            raise RuntimeError(
                "EventLoopThread.run() called from its own loop thread")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        # A coroutine submitted to a stopping/stopped loop would never be
        # awaited (RuntimeWarning now, a silent hang once callers wait on
        # the future); close it instead so best-effort notifications drop
        # cleanly at shutdown. A loop that merely hasn't *started* yet is
        # fine — run_coroutine_threadsafe queues onto it.
        if self._stopping or self.loop.is_closed():
            coro.close()
            return None
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        # RETAIN the future until done: the event loop keeps only WEAK
        # task references, and fire-and-forget callers drop this future
        # — a suspended task can then be garbage-collected mid-await,
        # surfacing as a spurious GeneratorExit inside the coroutine
        # (observed: pipelined actor creations dying with
        # "creation failed: GeneratorExit" under GC pressure).
        self._spawned.add(fut)
        fut.add_done_callback(self._spawned.discard)
        return fut

    def stop(self):
        self._stopping = True

        async def _drain():
            # Cancel every outstanding task, then AWAIT the cancellations:
            # stopping the loop in the same tick would strand tasks mid-
            # cancel ("Task was destroyed but it is pending!" at loop GC)
            # and leak their sockets/FDs.
            me = asyncio.current_task()
            deadline = self.loop.time() + 3
            for _ in range(10):  # handlers may spawn tasks while draining
                tasks = [t for t in asyncio.all_tasks(self.loop)
                         if t is not me]
                if not tasks:
                    break
                for t in tasks:
                    t.cancel()
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*tasks, return_exceptions=True),
                        max(0.1, deadline - self.loop.time()))
                # re-raising cancellation here would skip loop.stop()
                # below and hang the thread join — break IS the handling
                except (asyncio.TimeoutError,  # graftlint: ignore[swallow]
                        asyncio.CancelledError):
                    break
            self.loop.stop()

        def _kick():
            background(_drain())

        try:
            self.loop.call_soon_threadsafe(_kick)
        except RuntimeError:
            return  # loop already closed
        self.thread.join(timeout=5)
        if self.thread.is_alive():  # drain wedged: force the loop down
            try:
                self.loop.call_soon_threadsafe(self.loop.stop)
            except RuntimeError:
                pass
            self.thread.join(timeout=2)
