"""Prometheus text-format exporter over the GCS metrics table.

Reference analog: python/ray/_private/metrics_agent.py +
prometheus_exporter.py — there, each node's metrics agent exposes an
OpenCensus registry as a Prometheus scrape endpoint and the dashboard
proxies them. Here the GCS is already the aggregation point
(gcs.py handle_report_metrics / handle_get_metrics), so one scrape
endpoint on the dashboard (`GET /metrics`) renders the whole cluster:
no per-node agent fleet is needed for a TPU-pod-sized cluster, and the
scrape is consistent because it reads one table.

Layout produced (text exposition format 0.0.4):
  counters   -> `# TYPE name counter`  + `name{tags} value`
  gauges     -> `# TYPE name gauge`    + `name{tags} value`
  histograms -> `# TYPE name histogram` + `name_bucket{tags,le=...}`,
                `name_sum{tags}`, `name_count{tags}` (cumulative
                buckets, as Prometheus requires — the internal registry
                stores per-bucket counts non-cumulatively bounded by
                each `le`, which IS already cumulative: observe() adds
                to every bucket the value fits in; see
                util/metrics.py Histogram.observe).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Tuple

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")


def _sanitize_name(name: str) -> str:
    if _NAME_OK.match(name):
        return name
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not out or not re.match(r"[a-zA-Z_:]", out[0]):
        out = "_" + out
    return out


def _sanitize_label(name: str) -> str:
    if _LABEL_OK.match(name):
        return name
    out = re.sub(r"[^a-zA-Z0-9_]", "_", name)
    if not out or not re.match(r"[a-zA-Z_]", out[0]):
        out = "_" + out
    return out


def _escape_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _fmt_labels(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(
        f'{_sanitize_label(k)}="{_escape_value(str(v))}"'
        for k, v in sorted(tags.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(entries: Iterable[Dict[str, Any]]) -> str:
    """Render GCS metric entries (handle_get_metrics layout: name, kind,
    tags, value, description) as Prometheus exposition text."""
    # group by (name, kind) so TYPE/HELP headers appear once
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for e in entries:
        groups.setdefault((e["name"], e.get("kind", "gauge")), []).append(e)
    lines: List[str] = []
    for (name, kind), items in sorted(groups.items()):
        pname = _sanitize_name(name)
        desc = next((i.get("description") for i in items
                     if i.get("description")), "")
        if desc:
            lines.append(f"# HELP {pname} {_escape_value(desc)}")
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram"}.get(kind, "untyped")
        lines.append(f"# TYPE {pname} {ptype}")
        if kind == "histogram":
            # Prometheus requires buckets in ascending `le` order with
            # +Inf last, per series; the table hands rows back in
            # insertion order, which interleaves series and sorts "10"
            # before "2" lexically. Partition then sort numerically.
            buckets, sums, counts, strays = [], [], [], []
            for e in items:
                tags = dict(e.get("tags") or {})
                stat = tags.pop("__stat__", None)
                if stat == "sum":
                    sums.append((tags, e["value"]))
                elif stat == "count":
                    counts.append((tags, e["value"]))
                elif "le" in tags:
                    buckets.append((tags, e["value"]))
                else:
                    strays.append((tags, e["value"]))

            def _le_key(pair):
                tags, _ = pair
                le = tags["le"]
                series = sorted((k, v) for k, v in tags.items()
                                if k != "le")
                try:
                    bound = float("inf") if le == "+Inf" else float(le)
                except ValueError:
                    bound = float("inf")
                return (series, bound)

            # _sum/_count/stray lines sort by series labels too (buckets
            # already do): scrapes are diffable regardless of table
            # insertion order
            def _series_key(pair):
                tags, _ = pair
                return sorted(tags.items())

            for tags, value in sorted(buckets, key=_le_key):
                lines.append(f"{pname}_bucket{_fmt_labels(tags)} "
                             f"{_fmt_value(value)}")
            for tags, value in sorted(sums, key=_series_key):
                lines.append(f"{pname}_sum{_fmt_labels(tags)} "
                             f"{_fmt_value(value)}")
            for tags, value in sorted(counts, key=_series_key):
                lines.append(f"{pname}_count{_fmt_labels(tags)} "
                             f"{_fmt_value(value)}")
            # stray samples emit as untyped
            for tags, value in sorted(strays, key=_series_key):
                lines.append(f"{pname}{_fmt_labels(tags)} "
                             f"{_fmt_value(value)}")
        else:
            # counters/gauges: same deterministic series order
            plain = [(dict(e.get("tags") or {}), e["value"])
                     for e in items]
            for tags, value in sorted(
                    plain, key=lambda p: sorted(p[0].items())):
                lines.append(f"{pname}{_fmt_labels(tags)} "
                             f"{_fmt_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_cluster() -> str:
    """Scrape payload for the connected cluster: application metrics from
    the GCS table plus built-in cluster gauges (nodes/actors/tasks by
    state — the reference's metric_defs.h families)."""
    from ..util import state as state_api
    from .. import nodes as _nodes

    entries: List[Dict[str, Any]] = list(state_api.get_metrics())
    try:
        alive = sum(1 for n in _nodes() if n.get("Alive"))
        entries.append({"name": "ray_tpu_cluster_nodes", "kind": "gauge",
                        "tags": {}, "value": float(alive),
                        "description": "Alive nodes in the cluster"})
        for st, n in state_api.summarize_tasks().items():
            entries.append({
                "name": "ray_tpu_tasks", "kind": "gauge",
                "tags": {"state": st}, "value": float(n),
                "description": "Tasks by state (ref metric_defs.h tasks)"})
        actors = state_api.list_actors()
        by_state: Dict[str, int] = {}
        for a in actors:
            by_state[a.get("state", "UNKNOWN")] = (
                by_state.get(a.get("state", "UNKNOWN"), 0) + 1)
        for st, n in by_state.items():
            entries.append({
                "name": "ray_tpu_actors", "kind": "gauge",
                "tags": {"state": st}, "value": float(n),
                "description": "Actors by state"})
    except Exception:
        pass  # partial scrape beats a failed scrape
    return render(entries)
