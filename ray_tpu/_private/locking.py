"""Lock construction for the control plane.

Every instance lock in the Python planes is made here instead of via
bare ``threading.Lock()`` so the graftlint runtime witness (devtools/
graftlint/witness.py) can interpose: with ``lock_witness_enabled`` set
(``RAY_TPU_LOCK_WITNESS_ENABLED=1``, used by tests/CI stress runs),
every acquisition feeds a global lockdep-style order graph that raises
``LockOrderViolation`` — with both formation stacks — the moment two
threads establish inverted orders, instead of wedging silently later.

Production pays one config check per lock *construction* and zero cost
per acquisition.

The ``name`` is the lock's class in the witness graph: one name per
role ("ObjectStore._lock"), shared across instances.
"""

from __future__ import annotations

import threading

from .config import global_config


def witness_enabled() -> bool:
    return bool(getattr(global_config(), "lock_witness_enabled", False))


def make_lock(name: str):
    if witness_enabled():
        from ..devtools.graftlint.witness import WitnessLock

        return WitnessLock(name)
    return threading.Lock()


def make_rlock(name: str):
    if witness_enabled():
        from ..devtools.graftlint.witness import WitnessLock

        return WitnessLock(name, reentrant=True)
    return threading.RLock()


def make_condition(name: str):
    if witness_enabled():
        from ..devtools.graftlint.witness import make_condition as _mk

        return _mk(name)
    return threading.Condition()
