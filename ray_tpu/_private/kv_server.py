"""Standalone persistent KV store process — Redis's role for the GCS.

The reference achieves GCS fault tolerance by keeping its tables in an
external Redis (ref: src/ray/gcs/store_client/redis_store_client.h:111,
gcs_redis_failure_detector.h): losing the head node — its process AND
its disk — loses nothing, because a new GCS rebuilds from the store.
This process plays that role natively: the GCS's Storage facade streams
writes to it (`store_write_batch`), a (re)starting GCS seeds its tables
from `store_snapshot`, and the GCS's failure detector `store_ping`s it.

Persistence is the same journal machinery the local-file backend uses
(gcs_storage.Storage with a journal under --data), so compaction and
wire-version migration behave identically wherever the tables live. Run
it on a machine that survives the head node:

    python -m ray_tpu._private.kv_server --address /tmp/rtpu_kv.sock \
        --data /var/lib/rtpu_kv
    python -m ray_tpu._private.kv_server --address 0.0.0.0:6379 \
        --data /var/lib/rtpu_kv

or `ray-tpu kv-server` (scripts/cli.py).
"""

from __future__ import annotations

import argparse
import asyncio
import os
from typing import Optional

from .gcs_storage import Storage
from .rpc import RpcServer


class KvServer:
    def __init__(self, address: str, data_dir: str,
                 advertise_host: Optional[str] = None):
        os.makedirs(data_dir, exist_ok=True)
        self.storage = Storage(
            journal_path=os.path.join(data_dir, "kv_journal.bin"))
        self.server = RpcServer(address, name="rtpu-kv",
                                advertise_host=advertise_host)
        self.server.register("store_write_batch", self.handle_write_batch)
        self.server.register("store_snapshot", self.handle_snapshot)
        self.server.register("store_ping", self.handle_ping)

    async def start(self) -> str:
        await self.server.start()
        return self.server.address

    async def stop(self) -> None:
        await self.server.stop()
        self.storage.close()

    async def handle_write_batch(self, payload, conn):
        for op, ns, key, val in payload["ops"]:
            if op == "put":
                self.storage.put(ns, key, val)
            elif op == "del":
                self.storage.delete(ns, key)
        return True

    async def handle_snapshot(self, payload, conn):
        return list(self.storage.records())

    async def handle_ping(self, payload, conn):
        return True


async def _amain(address: str, data_dir: str) -> None:
    server = KvServer(address, data_dir)
    resolved = await server.start()
    print(f"rtpu-kv serving on {resolved} (data: {data_dir})", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()


def main() -> None:
    parser = argparse.ArgumentParser(
        description="ray_tpu external GCS store (the Redis role)")
    parser.add_argument("--address", required=True,
                        help="unix socket path or host:port")
    parser.add_argument("--data", required=True,
                        help="directory for the persistent journal")
    args = parser.parse_args()
    try:
        asyncio.run(_amain(args.address, args.data))
    except KeyboardInterrupt:  # graftlint: ignore[swallow] — quiet ^C exit
        pass


if __name__ == "__main__":
    main()
