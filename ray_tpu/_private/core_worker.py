"""Core worker: ownership, task submission, object access.

TPU-native analog of the reference core worker (ref: src/ray/core_worker/
core_worker.h:165, transport/normal_task_submitter.h, actor_task_submitter.h,
reference_count.h:66, task_manager.h). One CoreWorker per process (driver or
worker), bridging sync user code onto a dedicated asyncio IO thread.

Submission paths:
 * normal tasks — lease-based: acquire a worker lease from the raylet for the
   task's SchedulingKey (scheduling class), then push the task directly to the
   leased worker over its own socket (worker->worker direct push, the
   steady-state hot path; ref: normal_task_submitter.h:227). Leases are pooled
   per scheduling class and returned when the backlog drains.
 * actor tasks — pushed directly to the actor's worker with per-caller
   sequence numbers; the executing side replays them in order (ref:
   transport/sequential_actor_submit_queue.h, actor_scheduling_queue.h).

Ownership: this process owns every object its tasks return and everything it
`put`s. Local+borrowed reference counts drive plasma frees; submitted-task
argument deps pin refs until the task completes (ref: reference_count.h:66).
Lineage-based reconstruction is recorded (resubmittable task specs are kept
while their returns are referenced) — re-execution lands in the recovery
manager in a later milestone.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from .config import global_config
from . import locking
from .ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from .object_ref import ObjectRef, ObjectRefGenerator, _set_ref_registry
from .object_store import MemoryStore, SharedObjectStore
from .rpc import (ConnectionLost, EventLoopThread, RpcClient, RpcError,
                  background)
from . import serialization as ser
from .task_spec import (
    ArgKind,
    DefaultSchedulingStrategy,
    FunctionDescriptor,
    PlacementGroupSchedulingStrategy,
    ResourceSet,
    TaskArg,
    TaskSpec,
)
from .. import exceptions as exc

_SMALL = None  # resolved from config at init

# per-coroutine task binding for async actors (thread-locals cannot
# distinguish coroutines interleaving on one loop thread)
import contextvars

_task_ctx_var: "contextvars.ContextVar[Optional[TaskID]]" = \
    contextvars.ContextVar("ray_tpu_task_ctx", default=None)


@dataclass
class _ActorState:
    actor_id: ActorID
    address: str = ""
    state: str = "PENDING_CREATION"
    seq_no: int = 0
    client: Optional[RpcClient] = None
    waiters: List[asyncio.Future] = field(default_factory=list)
    death_cause: str = ""
    owned: bool = False                 # this process registered the actor
    creation_spec: Optional["TaskSpec"] = None
    restart_in_flight: bool = False


_STREAM_DONE = object()


def _rss_bytes() -> int:
    """Resident set size (the heap stat when tracemalloc is off)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # graftlint: ignore[swallow] — non-Linux /proc
        return 0       # miss: heap stat degrades to 0, never a fault

# tail-tolerance hedge counters, created lazily: metric construction
# spins up the flusher thread, which only processes that actually hedge
# should pay for
_hedge_counters: Dict[str, Any] = {}


def _hedge_counter(name: str):
    c = _hedge_counters.get(name)
    if c is None:
        from ..util.metrics import Counter
        c = _hedge_counters.setdefault(name, Counter(
            name, "tail-tolerance hedged-execution counter"))
    return c


# Submit-path stage timers (ROADMAP item 2's measured baseline): one
# histogram family, submit_stage_seconds{stage=...}, µs-resolution
# buckets (the stages live in the 1µs-1ms range — LATENCY_BUCKETS'
# 0.5ms floor would flatten them all into one bucket). Created lazily
# like the hedge counters so non-submitting processes never spin up
# the metrics flusher.
SUBMIT_STAGE_BUCKETS = [
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 0.1, 1.0]
_stage_hist_box: list = []


def _stage_hist():
    if not _stage_hist_box:
        from ..util.metrics import Histogram
        _stage_hist_box.append(Histogram(
            "submit_stage_seconds",
            "driver submit hot-path stage latency",
            boundaries=SUBMIT_STAGE_BUCKETS))
    return _stage_hist_box[0]


class _StageClock:
    """Consecutive perf_counter marks PARTITIONING submit_task into
    submit_stage_seconds{stage=...} observations — no gaps between
    marks, so the per-stage sums add up to the `total` stage minus
    observe overhead (the invariant tests/test_profiling.py and the
    bench_envelope submit family hold this family to)."""

    __slots__ = ("hist", "t0", "t")

    def __init__(self, hist):
        self.hist = hist
        self.t0 = self.t = time.perf_counter()

    def mark(self, stage: str) -> None:
        now = time.perf_counter()
        self.hist.observe(now - self.t, tags={"stage": stage})
        self.t = now

    def total(self) -> None:
        self.hist.observe(time.perf_counter() - self.t0,
                          tags={"stage": "total"})


@dataclass
class _StreamState:
    """Owner-side view of one streaming task's item queue (ref:
    task_manager.h ObjectRefStream)."""

    queue: asyncio.Queue = field(default_factory=asyncio.Queue)
    worker_address: str = ""
    consumed: int = 0
    received: int = 0
    total: Optional[int] = None


class _LeasePool:
    """Pooled worker leases for one scheduling class (ref: SchedulingKey lease
    pool, normal_task_submitter.h:58-65)."""

    def __init__(self):
        self.idle: List[dict] = []          # granted leases not executing
        self.in_flight = 0                  # lease requests outstanding
        self.waiters: List[asyncio.Future] = []

    def wake_one(self) -> None:
        while self.waiters:
            waiter = self.waiters.pop(0)
            if not waiter.done():
                waiter.set_result(None)
                return


class CoreWorker:
    def __init__(
        self,
        *,
        mode: str,                      # "driver" | "worker"
        session_name: str,
        gcs_address: str,
        raylet_address: str,
        job_id: JobID,
        node_id: NodeID,
        store: SharedObjectStore,
        io: Optional[EventLoopThread] = None,
        worker_id: Optional[WorkerID] = None,
    ):
        self.mode = mode
        self.session_name = session_name
        self.job_id = job_id
        self.node_id = node_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.store = store
        self.memory_store = MemoryStore()
        self.io = io or EventLoopThread(name=f"ray_tpu_io_{mode}")
        self.cfg = global_config()
        global _SMALL
        _SMALL = self.cfg.object_store_small_object_threshold

        self.gcs = RpcClient(gcs_address)
        self.raylet = RpcClient(raylet_address)
        self._worker_clients: Dict[str, RpcClient] = {}
        self._worker_clients_lock = asyncio.Lock()

        self._default_task_id = (TaskID.for_driver(job_id) if mode == "driver"
                                 else TaskID.for_normal_task(job_id))
        self._task_local = threading.local()  # per-execution-thread task context
        self._put_index = 0
        self._put_lock = locking.make_lock("CoreWorker._put_lock")
        self._subscribed_channels: set = set()
        self._actor_sub_tasks: Dict[str, asyncio.Task] = {}
        self._block_depth = 0          # worker dep-block nesting
        self._block_lock = locking.make_lock("CoreWorker._block_lock")

        # reference counting — native C++ table by default (ref:
        # reference_count.h:66; native/core_tables.cc), Python dicts as
        # the fallback when the toolchain can't build the lib
        self._rc = None
        try:
            from .._native import RefTable, native_unavailable_reason

            if native_unavailable_reason() is None:
                self._rc = RefTable()
        except Exception:
            self._rc = None
        self._local_refs: Dict[ObjectID, int] = {}
        self._borrowed: Dict[ObjectID, str] = {}
        self._task_deps: Dict[ObjectID, int] = {}
        self._ref_lock = locking.make_lock("CoreWorker._ref_lock")
        self._owned_in_plasma: set = set()

        # submission state
        self._lease_pools: Dict[int, _LeasePool] = {}
        self._actors: Dict[ActorID, _ActorState] = {}
        self._function_cache: Dict[str, Any] = {}
        self._exported_blobs: set = set()
        # id(func) -> (func, FunctionDescriptor); func kept so the id
        # cannot be recycled by a different object
        self._descriptor_cache: Dict[int, tuple] = {}
        # lineage: resubmittable specs for owned objects (recorded, replayed by
        # the recovery manager milestone)
        self._lineage: Dict[TaskID, TaskSpec] = {}
        self._runtime_env_cache: Dict[Any, Optional[dict]] = {}
        self._pg_rr = 0  # round-robin over bundles for wildcard PG leases
        self._pg_cache: Dict[Any, list] = {}  # pg_id -> bundle (node, addr)
        # object recovery (ref: object_recovery_manager.h): reconstruction
        # attempts consumed per lineage task
        self._reconstructions: Dict[TaskID, int] = {}
        # cancellation: in-flight normal tasks (ref: core_worker.cc Cancel)
        self._inflight: Dict[TaskID, dict] = {}
        # tail tolerance (The Tail at Scale): per-fn EMA of push->reply
        # durations (the owner-side latency profile hedge delays derive
        # from) + per-task events the raylet watchdog's hedge_hint RPC
        # sets to trigger an immediate hedge of a flagged task
        self._hedge_ema: Dict[str, float] = {}
        self._hedge_hints: Dict[str, asyncio.Event] = {}  # task hex -> event
        # object-locality hints: oid -> (node_hex, bytes) for sealed
        # plasma objects this owner knows about (its puts + its tasks'
        # large returns). Feeds locality-aware leasing (ref:
        # core_worker/lease_policy.h LocalityAwareLeasePolicy +
        # scheduling/policy/scorer.h): lease where the argument bytes
        # already live. Bounded FIFO — a hint, not a directory.
        self._obj_locality: "collections.OrderedDict" = (
            collections.OrderedDict())
        self._node_addr_cache: Dict[str, str] = {}
        self._node_addr_ts = 0.0
        # streaming generators (ref: task_manager.h ObjectRefStream)
        self._streams: Dict[TaskID, _StreamState] = {}
        # task events buffered toward the GCS (ref: task_event_buffer.h)
        self._task_events: List[dict] = []
        self._task_events_lock = locking.make_lock("CoreWorker._task_events_lock")
        self._task_event_flusher_armed = False
        self.address = ""  # worker-mode processes set their push address
        self._owner_server = None  # drivers: serves owned small objects

        # fast-lane submission plane (ray_tpu/_private/fastlane.py):
        # shm-ring task streaming to leased workers, asyncio as fallback
        from .fastlane import LanePool, lanes_enabled

        self._lane_events: Dict[ObjectID, threading.Event] = {}
        self._actor_lanes: Dict[ActorID, Any] = {}
        # serializes lane CREATION only (submission is lock-free):
        # constructing an ActorLane has side effects (spawns _attach,
        # registers shm rings named by (actor, worker, pid)) — two
        # threads racing the first call to an actor must not construct
        # two lanes whose identically-named rings clobber each other
        self._actor_lane_create_lock = locking.make_lock(
            "CoreWorker._actor_lane_create_lock")
        self._actor_lane_blocked: set = set()
        if lanes_enabled():
            # more lanes than cores just adds context-switch thrash: each
            # lane is a busy worker process (plus its reply thread here)
            width = max(1, min(self.cfg.fastlane_width,
                               os.cpu_count() or 1))
            self._lane_pool = LanePool(
                self, width=width, window=self.cfg.fastlane_window)
            self.io.spawn(self._lane_maintenance_loop())
        else:
            self._lane_pool = None

        # always-on sampling profiler for the DRIVER process (workers
        # start theirs in worker_main with task annotation); drained by
        # state.profile_cluster into the merged profile as "driver"
        self._driver_sampler = None
        if mode == "driver" and self.cfg.profiling_sample_hz > 0:
            from ..util import stacks as _stacks

            self._driver_sampler = _stacks.StackSampler(
                self.cfg.profiling_sample_hz,
                max_depth=self.cfg.profiling_max_stack_depth,
                name="stack_sampler").start()

        _set_ref_registry(self)

    def _on_reclaim_lease(self, payload):
        """Raylet push under pending demand: give back the named lane's
        lease if it has nothing in flight."""
        if self._lane_pool is not None:
            self._lane_pool.reclaim(payload.get("lease_id"))

    async def _lane_maintenance_loop(self):
        while True:
            await asyncio.sleep(2.0)
            if self._lane_pool is not None:
                self._lane_pool.maintain()

    # ------------------------------------------------------- task context
    @property
    def current_task_id(self) -> TaskID:
        ctx = _task_ctx_var.get()
        if ctx is not None:
            return ctx
        return getattr(self._task_local, "task_id", None) or self._default_task_id

    @current_task_id.setter
    def current_task_id(self, task_id: TaskID) -> None:
        self._default_task_id = task_id

    def set_task_context(self, task_id: TaskID) -> None:
        """Bind the executing task to this thread (concurrent actor methods
        each get their own context, so put-object lineage stays correct)."""
        self._task_local.task_id = task_id

    def clear_task_context(self) -> None:
        self._task_local.task_id = None

    def set_async_task_context(self, task_id: TaskID) -> None:
        """Bind the executing task to the current coroutine context: async
        actor methods interleave on ONE loop thread, so thread-locals
        cannot tell them apart — contextvars can."""
        _task_ctx_var.set(task_id)

    # ------------------------------------------------------------- lifecycle
    def connect(self):
        self.io.run(self._connect())

    async def _connect(self):
        await self.gcs.connect()
        await self.raylet.connect()
        self.gcs.on_push("pubsub:actor", self._on_actor_update)
        self.raylet.on_push("reclaim_lease", self._on_reclaim_lease)
        # actor updates are subscribed PER ACTOR (actor:<hex>) on first
        # contact with a handle — a blanket "actor" subscription from
        # every worker makes each lifecycle event an O(workers) fan-out
        # (quadratic at 1k-actor envelope depth)
        self.gcs.on_reconnect.append(self._resubscribe_gcs)
        if self.mode == "driver" and not self.address:
            await self._start_owner_server()

    async def _start_owner_server(self):
        """Drivers serve their owned in-memory objects to borrowers
        (ref: core_worker.proto GetObject — the owner is the source of
        truth for small objects, which never touch plasma). Workers
        register the same handler on their existing task server."""
        from .rpc import RpcServer, parse_address

        kind = parse_address(self.raylet.address)
        if kind[0] == "unix":
            base = os.path.dirname(kind[1])
            addr = os.path.join(
                base, f"driver_{self.worker_id.hex()[:12]}.sock")
        else:
            addr = "127.0.0.1:0"
        self._owner_server = RpcServer(
            addr, name=f"owner-{self.worker_id.hex()[:8]}")
        self._owner_server.register("fetch_object", self._handle_fetch_object)
        self._owner_server.register("hedge_hint", self.handle_hedge_hint)
        await self._owner_server.start()
        self.address = self._owner_server.address

    async def handle_hedge_hint(self, payload, conn=None):
        """Raylet watchdog push: the named task is flagged as stalled —
        hedge it now instead of waiting out the owner-side delay. Workers
        register this on their task server, drivers on the owner server
        (the same split as fetch_object)."""
        tid = payload.get("task_id")
        if hasattr(tid, "hex"):
            tid = tid.hex()
        ev = self._hedge_hints.get(tid)
        if ev is not None:
            ev.set()
        return True

    async def _handle_fetch_object(self, payload, conn):
        """Serve one owned object: {"status": ok|in_plasma|pending|gone}.
        pending = the creating task is still in flight here, the
        borrower should retry. in_plasma = the object is sealed in this
        node's store and too large to pickle through the control RPC —
        the borrower pulls it through its raylet (the bulk transfer
        plane), landing it sealed in ITS node store where every local
        worker shares it."""
        oid = payload["object_id"]
        data = self.memory_store.get(oid)
        if data is None:
            view = self.store.get(oid)
            if view is not None:
                if len(view) > self.cfg.object_store_small_object_threshold:
                    return {"status": "in_plasma", "size": len(view),
                            "data": None}
                data = bytes(view)
        if data is not None:
            return {"status": "ok", "data": data}
        if (oid in self._lane_events or oid.task_id() in self._inflight
                or oid.task_id() in self._streams):
            return {"status": "pending", "data": None}
        return {"status": "gone", "data": None}

    def shutdown(self):
        if self._driver_sampler is not None:
            self._driver_sampler.stop(timeout=2.0)
            self._driver_sampler = None
        if self._lane_pool is not None:
            self._lane_pool.close()
        for lane in list(self._actor_lanes.values()):
            lane.close()
        self._actor_lanes.clear()
        try:
            self.io.run(self._shutdown(), timeout=5)
        except Exception:
            pass
        self.io.stop()
        _set_ref_registry(None)
        # The native RefTable is deliberately NOT closed: ObjectRef
        # finalizers and lane reply threads may still race a call into
        # it during interpreter teardown, and close() would free the C++
        # table under them (use-after-free). It is in-process memory —
        # process exit reclaims it.

    async def _shutdown(self):
        # final task-event drain: events recorded moments before
        # shutdown would otherwise miss the 250ms flusher and vanish
        # from the state API / `timeline` (observed: a short driver's
        # FINISHED events lost)
        with self._task_events_lock:
            flush, self._task_events = self._task_events, []
        if flush and not self.gcs.closed:
            try:
                # 1s cap: this whole coroutine runs under a 5s budget
                # and driver_exit + connection closes must still fit
                await asyncio.wait_for(self._send_task_events(flush), 1)
            except Exception:
                pass
        if self.mode == "driver" and not self.gcs.closed:
            try:
                # clean detach: the GCS tears down this job's non-detached
                # actors immediately instead of waiting out the
                # connection-drop grace window
                await self.gcs.call("driver_exit", {"job_id": self.job_id},
                                    timeout=3)
            except Exception:
                pass
        for task in list(self._worker_clients.values()):
            try:
                client = await asyncio.wait_for(asyncio.shield(task), 1.0)
                await client.close()
            except Exception:
                pass
        if self._owner_server is not None:
            try:
                await self._owner_server.stop()
            except Exception:
                pass
        await self.gcs.close()
        await self.raylet.close()

    async def _resubscribe_gcs(self):
        """A restarted GCS dropped this connection's subscriptions;
        re-establish every channel this core ever subscribed."""
        try:
            await self.gcs.call("subscribe", {
                "channels": sorted(self._subscribed_channels)})
        except Exception:
            pass

    # --------------------------------------------------- app-level pubsub
    def subscribe_channel(self, channel: str, callback) -> None:
        """Receive pushes on an application pubsub channel (the long-poll
        replacement surface — ref: serve/_private/long_poll.py:66; here
        pushes ride the standing GCS connection)."""
        self.gcs.on_push("pubsub:" + channel, callback)
        self._subscribed_channels.add(channel)
        self.io.run(self.gcs.call("subscribe", {"channels": [channel]}),
                    timeout=10)

    def publish_channel(self, channel: str, message) -> None:
        self.io.run(self.gcs.call("publish", {
            "channel": channel, "message": message}), timeout=10)

    # ------------------------------------------------- blocked notification
    def _notify_blocked(self):
        """Worker mode: tell the raylet this worker's task is blocked on
        object resolution so the lease's CPU is released back (ref:
        NotifyDirectCallTaskBlocked — see raylet.handle_worker_blocked).
        Re-entrant; no-op for drivers."""
        if self.mode != "worker":
            return
        with self._block_lock:
            self._block_depth += 1
            first = self._block_depth == 1
        if first:
            try:
                self.io.run(self.raylet.call(
                    "worker_blocked", {"worker_id": self.worker_id},
                    timeout=5), timeout=6)
            except Exception:
                pass

    def _notify_unblocked(self):
        if self.mode != "worker":
            return
        with self._block_lock:
            self._block_depth = max(0, self._block_depth - 1)
            last = self._block_depth == 0
        if last:
            try:
                self.io.run(self.raylet.call(
                    "worker_unblocked", {"worker_id": self.worker_id},
                    timeout=5), timeout=6)
            except Exception:
                pass

    # -------------------------------------------------------- ref counting
    # Native C++ table when available (self._rc, native/core_tables.cc);
    # the table returns the free decision: 0 keep, 1 free (owned),
    # 2 drop local state only (borrowed).
    def add_local_ref(self, oid: ObjectID):
        if self._rc is not None:
            self._rc.add_local(oid.binary())
            return
        with self._ref_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1

    def remove_local_ref(self, oid: ObjectID):
        if self._rc is not None:
            self._apply_free_decision(oid, self._rc.remove_local(oid.binary()))
            return
        with self._ref_lock:
            count = self._local_refs.get(oid, 0) - 1
            if count <= 0:
                self._local_refs.pop(oid, None)
                if self._task_deps.get(oid, 0) <= 0:
                    self._maybe_free(oid)
            else:
                self._local_refs[oid] = count

    def add_borrowed_ref(self, oid: ObjectID, owner_address: str):
        self._borrowed[oid] = owner_address
        if self._rc is not None:
            self._rc.set_borrowed(oid.binary())
            return
        with self._ref_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1

    def _pin_task_dep(self, oid: ObjectID):
        if self._rc is not None:
            self._rc.pin_dep(oid.binary())
            return
        with self._ref_lock:
            self._task_deps[oid] = self._task_deps.get(oid, 0) + 1

    def _unpin_task_dep(self, oid: ObjectID):
        if self._rc is not None:
            self._apply_free_decision(oid, self._rc.unpin_dep(oid.binary()))
            return
        with self._ref_lock:
            count = self._task_deps.get(oid, 0) - 1
            if count <= 0:
                self._task_deps.pop(oid, None)
                if self._local_refs.get(oid, 0) <= 0:
                    self._maybe_free(oid)
            else:
                self._task_deps[oid] = count

    def _apply_free_decision(self, oid: ObjectID, decision: int):
        if decision == 0:
            return
        if decision == 2:  # borrowed: drop local state, owner frees
            self._borrowed.pop(oid, None)
            return
        self._free_owned(oid)

    def _maybe_free(self, oid: ObjectID):
        # only the owner frees plasma copies; borrowers just drop local state
        if oid in self._borrowed:
            self._borrowed.pop(oid, None)
            return
        self._free_owned(oid)

    def _free_owned(self, oid: ObjectID):
        self.memory_store.delete(oid)
        if oid in self._owned_in_plasma:
            self._owned_in_plasma.discard(oid)
            spec = self._lineage.pop(oid.task_id(), None)
            del spec
            if not self.gcs.closed:
                self.io.spawn(self._free_remote([oid]))

    async def _free_remote(self, oids: List[ObjectID]):
        try:
            await self.raylet.call("free_objects", {"object_ids": oids})
        except Exception:
            pass

    # -------------------------------------------------- memory attribution
    def local_memory_report(self) -> dict:
        """This process's object-reference claims + heap stats: the
        per-process half of state.memory_report (the GCS merges claims
        from every worker — plus the driver's, passed through the call
        payload — against each node's store inventory to attribute
        bytes per owner/ref-type)."""
        import sys as _sys
        import tracemalloc

        claims: Dict[str, dict] = {}

        def _claim(oid: ObjectID) -> dict:
            rec = claims.get(oid.hex())
            if rec is None:
                rec = claims[oid.hex()] = {
                    "local_refs": 0, "task_deps": 0, "owned": False,
                    "borrowed_from": None}
            return rec

        with self._ref_lock:
            owned = set(self._owned_in_plasma)
            borrowed = dict(self._borrowed)
            local_refs = dict(self._local_refs)
            task_deps = dict(self._task_deps)
        for oid in owned:
            _claim(oid)["owned"] = True
        for oid, owner in borrowed.items():
            _claim(oid)["borrowed_from"] = owner
        if self._rc is not None:
            # native RefTable: counts are queryable per oid but the
            # table is not enumerable — owned/borrowed sets bound the
            # plasma-relevant oids (everything else is memory-store)
            for oid in set(owned) | set(borrowed):
                rec = _claim(oid)
                try:
                    rec["local_refs"] = self._rc.local_count(oid.binary())
                    if rec["local_refs"] == 0 and \
                            self._rc.contains(oid.binary()):
                        # alive with zero local refs: held by a task-dep
                        # pin (the table has no per-kind count getter)
                        rec["task_deps"] = 1
                except Exception:  # graftlint: ignore[swallow] — native
                    pass           # table probe is advisory enrichment
        else:
            for oid, n in local_refs.items():
                _claim(oid)["local_refs"] = n
            for oid, n in task_deps.items():
                _claim(oid)["task_deps"] = n
        report = {
            "address": self.address,
            "worker_id": self.worker_id.hex(),
            "pid": os.getpid(),
            "mode": self.mode,
            "num_inflight_tasks": len(self._inflight),
            "memory_store": self.memory_store.usage_report(),
            "claims": claims,
        }
        if tracemalloc.is_tracing():
            current, peak = tracemalloc.get_traced_memory()
            report["heap"] = {"kind": "tracemalloc",
                              "current_bytes": current,
                              "peak_bytes": peak}
        else:
            report["heap"] = {"kind": "rss", "current_bytes": _rss_bytes()}
        try:
            from ..util import hbm

            report["hbm"] = (hbm.collect_hbm_stats()
                             if "jax" in _sys.modules else [])
        except Exception:
            report["hbm"] = []
        return report

    # ----------------------------------------------------------- task events
    def _record_task_event(self, task_id: TaskID, **fields) -> None:
        """Buffer a task state transition; a standing periodic flusher
        ships batches to the GCS (ref: task_event_buffer.h →
        gcs_task_manager.h). Nothing is spawned on the submit path —
        at 10k tasks/s even one run_coroutine_threadsafe per event
        would dominate."""
        event = {"task_id": task_id}
        event.update(fields)
        with self._task_events_lock:
            # lifecycle transitions coalesce into the tail event when it
            # is for the same task (one merged GCS record update instead
            # of N) — everything else appends
            if ("transitions" in event and self._task_events
                    and self._task_events[-1]["task_id"] == task_id):
                tail = self._task_events[-1]
                tail.setdefault("transitions", []).extend(
                    event.pop("transitions"))
                tail.update({k: v for k, v in event.items()
                             if k != "task_id"})
                return
            # bounded buffer: a submit burst must not build an unbounded
            # flush payload that then monopolizes the GCS loop (observed
            # r4: flush backlog starving actor creations). Oldest events
            # drop first, like the reference's ring buffer
            # (task_event_buffer.h kMaxBufferedTaskEvents).
            if len(self._task_events) >= self._TASK_EVENT_BUFFER_MAX:
                del self._task_events[:self._TASK_EVENT_FLUSH_MAX]
                self._task_events_dropped += self._TASK_EVENT_FLUSH_MAX
            self._task_events.append(event)
            arm = not self._task_event_flusher_armed
            if arm:
                self._task_event_flusher_armed = True
        if arm:
            self.io.spawn(self._task_event_flusher())

    def _record_transition(self, task_id: TaskID, to_state: str,
                           ts: Optional[float] = None, **fields) -> None:
        """Append one lifecycle transition {state, ts, node_id} to the
        task's state_transitions list in the GCS task table (the flight
        recorder's unit record). Extra fields ride the same event as
        last-writer-wins record fields (e.g. state/node_id/worker_id —
        hence the positional name: ``state=`` means the record field)."""
        entry = {"state": to_state,
                 "ts": time.time() if ts is None else ts,
                 "node_id": self.node_id.hex()}
        self._record_task_event(task_id, transitions=[entry], **fields)

    _TASK_EVENT_FLUSH_MAX = 2000     # events per report RPC
    _TASK_EVENT_BUFFER_MAX = 100_000
    _task_event_flusher_armed = False
    _task_events_dropped = 0

    async def _task_event_flusher(self):
        """Standing flusher; exits after an idle period so short-lived
        cores don't keep a wakeup loop alive. Flushes in BOUNDED chunks:
        each chunk is one awaited GCS RPC, so control-plane traffic
        (lease grants, actor registration) interleaves between chunks
        instead of queueing behind one giant report."""
        idle = 0
        while idle < 20:
            await asyncio.sleep(0.25)
            with self._task_events_lock:
                flush, self._task_events = self._task_events, []
            if flush:
                idle = 0
                for i in range(0, len(flush), self._TASK_EVENT_FLUSH_MAX):
                    await self._send_task_events(
                        flush[i:i + self._TASK_EVENT_FLUSH_MAX])
            else:
                idle += 1
        with self._task_events_lock:
            if self._task_events:
                # an event landed between the last empty swap and now;
                # disarming here would strand it — let a fresh flusher
                # take over
                self.io.spawn(self._task_event_flusher())
            else:
                self._task_event_flusher_armed = False

    async def _send_task_events(self, events: List[dict]):
        try:
            await self.gcs.call("report_task_events", {"events": events})
        except Exception:
            pass

    # --------------------------------------------------------------- put/get
    def put(self, value: Any) -> ObjectRef:
        with self._put_lock:
            self._put_index += 1
            oid = ObjectID.for_put(self.current_task_id, self._put_index)
        parts = ser.serialize_parts(value)
        if parts.total <= _SMALL:
            self._store_object(oid, parts.to_bytes())
        else:
            # large objects serialize straight into the shm mapping —
            # one write pass instead of assemble + bytes() + store copy
            buf = self.store.create(oid, parts.total)
            try:
                parts.write_into(buf)
            except BaseException:
                self.store.abort(oid)
                raise
            self.store.seal(oid)
            self._owned_in_plasma.add(oid)
            self._note_locality(oid, self.node_id.hex(), parts.total)
            self.io.spawn(self._notify_sealed(oid, parts.total))
        return ObjectRef(oid, self.address)

    def _store_object(self, oid: ObjectID, data: bytes, memory_only: bool = False):
        if len(data) <= _SMALL or memory_only:
            self.memory_store.put(oid, data)
            if not memory_only:
                # small objects also become visible cluster-wide via plasma so
                # other processes can fetch them (inline-on-reply covers the
                # common path; this covers puts)
                self.store.put(oid, data)
                self._owned_in_plasma.add(oid)
                self.io.spawn(self._notify_sealed(oid, len(data)))
        else:
            self.store.put(oid, data)
            self._owned_in_plasma.add(oid)
            self._note_locality(oid, self.node_id.hex(), len(data))
            self.io.spawn(self._notify_sealed(oid, len(data)))

    async def _notify_sealed(self, oid: ObjectID, size: int):
        try:
            # idempotent: retried on loss so the object directory cannot
            # silently miss a sealed object (chaos/unreliable transports)
            await self.raylet.call_retrying(
                "object_sealed", {"object_id": oid, "size": size},
                attempts=5, per_try_timeout=2.0)
        except Exception:
            pass

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        oids = [r.id() for r in refs]
        # Fast path: every object is already local, or is the pending
        # return of a fast-lane task (completed by the lane reply thread
        # setting a threading.Event) — no event-loop hop, no raylet RPC.
        fast = []
        for oid in oids:
            ev = self._lane_events.get(oid)
            if ev is not None:
                fast.append((oid, ev))
            elif self.memory_store.contains(oid) or self.store.contains(oid):
                fast.append((oid, None))
            else:
                fast = None
                break
        if fast is not None:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            # notify only for a REAL wait: pre-set events / already-
            # completed results must not cost two raylet RPCs
            waiting = any(
                ev is not None and not ev.is_set()
                and not (self.memory_store.contains(oid)
                         or self.store.contains(oid))
                for oid, ev in fast)
            if waiting:
                self._notify_blocked()
            try:
                out = []
                for oid, ev in fast:
                    if ev is not None and not (
                            self.memory_store.contains(oid)
                            or self.store.contains(oid)):
                        left = (None if deadline is None
                                else max(0.0, deadline - time.monotonic()))
                        if not ev.wait(left):
                            raise exc.GetTimeoutError(
                                "Get timed out: fast-lane task not finished")
                    out.append(self._load_object(oid))
                return out
            finally:
                if waiting:
                    self._notify_unblocked()
        owners = {r.id(): r.owner_address for r in refs if r.owner_address}
        # fast==None means at least one object is neither local nor an
        # in-flight lane return: a real wait — give the CPU back
        self._notify_blocked()
        try:
            return self.io.run(
                self._get(oids, timeout, owners),
                timeout=None if timeout is None else timeout + 30)
        finally:
            self._notify_unblocked()

    async def _probe_owner(self, owner: str, oid: ObjectID,
                           rpc_timeout: float = 10.0) -> str:
        """One non-blocking probe of an object's owner. "ok" lands the
        bytes in the local memory store; "pending" means the creating
        task is still running there. Returns
        "ok" | "pending" | "gone" | "unreachable"."""
        try:
            client = await self._client_for(owner)
            reply = await client.call("fetch_object",
                                      {"object_id": oid},
                                      timeout=rpc_timeout)
        except Exception:
            return "unreachable"  # owner dead, hung, or not serving
        if reply is None or reply.get("status") == "gone":
            return "gone"
        if reply["status"] == "ok":
            self.memory_store.put(oid, reply["data"])
            return "ok"
        if reply["status"] == "in_plasma":
            return "in_plasma"  # caller routes through the raylet pull
        return "pending"

    async def _owner_gone_policy(self, oid: ObjectID,
                                 gone_strikes: Dict[ObjectID, int]) -> str:
        """Shared _get/_wait policy when an owner reports gone or is
        unreachable: the owner holds nothing IN MEMORY, but a large
        result seals into plasma on the EXECUTING node, so give the
        raylet directory a few passes (with a grace window for the
        batched seal report) before attempting lineage recovery.
        Returns "directory" (keep consulting the directory),
        "recovered", or "lost"."""
        strikes = gone_strikes.get(oid, 0) + 1
        gone_strikes[oid] = strikes
        if strikes < 4:
            return "directory"
        if await self._try_recover([oid]):
            gone_strikes.pop(oid, None)
            return "recovered"
        return "lost"

    async def _fetch_from_owner(self, owner: str, oid: ObjectID,
                                deadline: Optional[float]) -> str:
        """Pull one object from its owner into the local memory store
        (small objects never seal into plasma — the owner serves them).
        Retries while the owner reports the creating task pending.
        Returns "ok" | "in_plasma" | "gone" | "unreachable" | "timeout"."""
        delay = 0.005
        while True:
            status = await self._probe_owner(owner, oid)
            if status != "pending":
                return status
            if (deadline is not None
                    and asyncio.get_event_loop().time() > deadline):
                return "timeout"
            await asyncio.sleep(delay)
            delay = min(delay * 2, 0.1)

    async def _get(self, oids: List[ObjectID], timeout: Optional[float],
                   owners: Optional[Dict[ObjectID, str]] = None) -> List[Any]:
        """Resolution order per object: local stores → (owned, task in
        flight here) poll local completion → (borrowed, owner known)
        fetch from owner → raylet directory wait + lineage recovery.
        Small objects never seal into plasma, so the directory only
        covers large/sealed ones."""
        loop = asyncio.get_event_loop()
        deadline = None if timeout is None else loop.time() + timeout
        owners = owners or {}
        delay = 0.002
        gone_strikes: Dict[ObjectID, int] = {}
        while True:
            missing = [oid for oid in oids
                       if not self.memory_store.contains(oid)
                       and not self.store.contains(oid)]
            if not missing:
                return [self._load_object(oid) for oid in oids]
            pending_here = {oid for oid in missing
                            if oid in self._lane_events
                            or oid.task_id() in self._inflight
                            or oid.task_id() in self._streams}
            foreign = [oid for oid in missing if oid not in pending_here]
            progressed = False
            plasma_wait = []
            for oid in foreign:
                owner = owners.get(oid)
                if owner and owner != self.address:
                    status = await self._fetch_from_owner(owner, oid,
                                                          deadline)
                    if status == "ok":
                        progressed = True
                        continue
                    if status == "in_plasma":
                        # sealed + large at the owner's node: pull it
                        # through the raylet (bulk transfer plane)
                        plasma_wait.append(oid)
                        continue
                    if status in ("gone", "unreachable"):
                        verdict = await self._owner_gone_policy(
                            oid, gone_strikes)
                        if verdict == "recovered":
                            continue
                        if verdict == "lost":
                            raise exc.ObjectLostError(oid)
                        plasma_wait.append(oid)
                        continue
                    raise exc.GetTimeoutError(
                        f"Get timed out waiting on owner {owner}")
                plasma_wait.append(oid)
            if plasma_wait:
                left = (None if deadline is None
                        else max(0.0, deadline - loop.time()))
                # bounded slices when owned work is also pending here or
                # an owner said gone (the directory may never learn of a
                # small object), so local completions / strikes progress
                slice_t = left
                if pending_here or gone_strikes:
                    slice_t = 0.2 if left is None else min(0.2, left)
                reply = await self.raylet.call("wait_objects", {
                    "object_ids": plasma_wait,
                    "num_returns": len(plasma_wait),
                    "timeout": slice_t,
                })
                lost = reply.get("lost", [])
                if lost:
                    recovered = await self._try_recover(lost)
                    if not recovered:
                        raise exc.ObjectLostError(lost[0])
                    continue
                if len(reply["ready"]) >= len(plasma_wait):
                    progressed = True
                elif not pending_here and timeout is not None and (
                        deadline is None or loop.time() >= deadline):
                    raise exc.GetTimeoutError(
                        f"Get timed out: "
                        f"{len(plasma_wait) - len(reply['ready'])} "
                        f"object(s) not ready")
            if deadline is not None and loop.time() >= deadline:
                still = [oid for oid in oids
                         if not self.memory_store.contains(oid)
                         and not self.store.contains(oid)]
                if still:
                    raise exc.GetTimeoutError(
                        f"Get timed out: {len(still)} object(s) not ready")
                continue
            if not progressed:
                await asyncio.sleep(delay)
                delay = min(delay * 2, 0.05)
            else:
                delay = 0.002

    async def _try_recover(self, oids: List[ObjectID]) -> bool:
        """Lineage reconstruction (ref: object_recovery_manager.h,
        task_manager.h resubmit): re-execute the recorded creating task of
        each lost object, recursively recovering lost arguments first.
        Bounded by the task's max_retries. False = any object unrecoverable
        (no lineage: ray_tpu.put data, actor returns, exhausted budget)."""
        for oid in dict.fromkeys(oids):
            if not await self._recover_object(oid):
                return False
        return True

    async def _recover_object(self, oid: ObjectID, depth: int = 0) -> bool:
        if depth > 16:
            return False
        if self.memory_store.contains(oid) or self.store.contains(oid):
            return True
        spec = self._lineage.get(oid.task_id())
        if spec is None or spec.actor_id is not None or spec.streaming:
            return False
        if spec.max_retries <= 0:
            return False
        used = self._reconstructions.get(spec.task_id, 0)
        if used >= spec.max_retries:
            return False
        self._reconstructions[spec.task_id] = used + 1
        # lost args must be rebuilt before the task can run again; args that
        # are merely remote are pulled by the executing raylet as usual
        for arg in spec.args:
            if arg.kind != ArgKind.OBJECT_REF:
                continue
            reply = await self.raylet.call("wait_objects", {
                "object_ids": [arg.object_id], "num_returns": 1, "timeout": 0})
            if reply.get("lost"):
                await self.raylet.call(
                    "forget_lost", {"object_ids": [arg.object_id]})
                if not await self._recover_object(arg.object_id, depth + 1):
                    return False
        # clear sticky lost markers so the fresh copy can be awaited
        await self.raylet.call("forget_lost", {"object_ids": spec.return_ids()})
        try:
            await self._run_on_leased_worker(spec)
        except asyncio.CancelledError:
            raise  # recovery itself cancelled: don't report "lost"
        except Exception:  # any resubmit failure surfaces as "lost"
            return False
        return True

    def _load_object(self, oid: ObjectID) -> Any:
        data = self.memory_store.get(oid)
        if data is None:
            view = self.store.get(oid)
            if view is None:
                raise exc.ObjectLostError(oid)
            data = view
        value, metadata = ser.deserialize(data)
        if metadata == ser.META_ERROR:
            err, tb = value
            if isinstance(err, (exc.TaskCancelledError, exc.ActorDiedError,
                                exc.WorkerCrashedError, exc.ObjectLostError)):
                raise err
            raise exc.TaskError(err, tb)
        return value

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        oids = [r.id() for r in refs]
        owners = {r.id(): r.owner_address for r in refs if r.owner_address}
        ready_ids = self.io.run(self._wait(oids, num_returns, timeout, owners))
        ready_set = set(ready_ids[:num_returns]) if len(ready_ids) > num_returns else set(ready_ids)
        ready, not_ready = [], []
        for ref in refs:
            (ready if ref.id() in ready_set and len(ready) < num_returns else not_ready).append(ref)
        return ready, not_ready

    async def _wait(self, oids, num_returns, timeout, owners=None):
        """Readiness: local stores first; owned in-flight tasks (fast
        lane / asyncio) complete into the memory store, so they are
        polled locally — small returns never reach the plasma
        directory; borrowed refs with a known foreign owner are probed
        at that owner (small objects never get a directory entry, so
        the raylet wait manager alone would never report them ready);
        everything else blocks on the raylet wait manager. Lost
        objects count as ready: their get() surfaces ObjectLostError
        (matches the reference, where a failed reconstruction stores
        an error object)."""
        loop = asyncio.get_event_loop()
        deadline = None if timeout is None else loop.time() + timeout
        owners = owners or {}
        delay = 0.002
        lost_here: set = set()
        gone_strikes: Dict[ObjectID, int] = {}
        while True:
            ready = [oid for oid in oids
                     if oid in lost_here
                     or self.memory_store.contains(oid)
                     or self.store.contains(oid)]
            if len(ready) >= num_returns:
                return ready
            ready_set = set(ready)
            pending_here = {oid for oid in oids
                            if oid not in ready_set
                            and (oid in self._lane_events
                                 or oid.task_id() in self._inflight
                                 or oid.task_id() in self._streams)}
            owner_served = [oid for oid in oids
                            if oid not in ready_set
                            and oid not in pending_here
                            and owners.get(oid) not in (None, self.address)]
            owner_set = set(owner_served)
            remote = [oid for oid in oids
                      if oid not in ready_set and oid not in pending_here
                      and oid not in owner_set]
            progressed = False
            for oid in owner_served:
                # cap each probe RPC by the caller's remaining budget so
                # a hung owner cannot make wait(timeout=0.5) take 10 s
                left = (None if deadline is None
                        else max(0.0, deadline - loop.time()))
                rpc_t = 10.0 if left is None else max(0.05, min(10.0, left))
                status = await self._probe_owner(owners[oid], oid,
                                                 rpc_timeout=rpc_t)
                if status == "ok":
                    progressed = True
                elif status == "in_plasma":
                    remote.append(oid)  # directory wait pulls it locally
                elif status in ("gone", "unreachable"):
                    # lost counts as ready; get() raises there
                    verdict = await self._owner_gone_policy(
                        oid, gone_strikes)
                    if verdict in ("recovered", "lost"):
                        if verdict == "lost":
                            lost_here.add(oid)
                        progressed = True
                    else:
                        remote.append(oid)
                if deadline is not None and loop.time() >= deadline:
                    break
            if progressed:
                continue
            if remote and not pending_here and not owner_served:
                left = (None if deadline is None
                        else max(0.0, deadline - loop.time()))
                reply = await self.raylet.call("wait_objects", {
                    "object_ids": remote,
                    "num_returns": num_returns - len(ready),
                    "timeout": left if timeout is not None else None,
                })
                return ready + reply["ready"] + reply.get("lost", [])
            if remote:
                reply = await self.raylet.call("wait_objects", {
                    "object_ids": remote, "num_returns": len(remote),
                    "timeout": 0})
                combined = ready + reply["ready"] + reply.get("lost", [])
                if len(combined) >= num_returns:
                    return combined
            if deadline is not None and loop.time() >= deadline:
                return ready
            await asyncio.sleep(delay)
            # owner-probe-only passes may spin for a task's whole
            # runtime (no blocking park exists for borrowed pending
            # objects) — back off further so a minutes-long wait costs
            # ~4 RPCs/s, not ~20; local in-flight completion still
            # polls at the tight cap.
            cap = 0.25 if (owner_served and not pending_here) else 0.05
            delay = min(delay * 2, cap)

    def as_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()

        async def _resolve():
            try:
                values = await self._get([ref.id()], None)
                fut.set_result(values[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        self.io.spawn(_resolve())
        return fut

    # ------------------------------------------------------ function export
    def export_function(self, func_or_class: Any) -> FunctionDescriptor:
        # Descriptor memoized per function OBJECT: cloudpickling the
        # function on every submit would dominate the trivial-task path
        # (~130us each). Keyed by identity in a WeakKeyDictionary-like
        # id map so redefinition (new object) re-exports; the closure
        # caveat (mutated captured state is not re-shipped) matches the
        # reference's once-per-function export via function_manager.py.
        key = id(func_or_class)
        cached = self._descriptor_cache.get(key)
        if cached is not None and cached[0] is func_or_class:
            return cached[1]
        if len(self._descriptor_cache) >= 4096:
            # bound the cache: drivers minting closures in a loop must
            # not pin every one (plus its captures) forever
            for old in list(self._descriptor_cache)[:2048]:
                self._descriptor_cache.pop(old, None)
        pickled = cloudpickle.dumps(func_or_class)
        blob_id = FunctionDescriptor.blob_id_for(pickled)
        if blob_id not in self._exported_blobs:
            self.io.run(self.gcs.call("kv_put", {
                "ns": "functions", "key": blob_id, "value": pickled,
            }))
            self._exported_blobs.add(blob_id)
        name = getattr(func_or_class, "__qualname__", repr(func_or_class))
        descriptor = FunctionDescriptor(blob_id=blob_id, repr_name=name)
        self._descriptor_cache[key] = (func_or_class, descriptor)
        return descriptor

    def load_function(self, blob_id: str) -> Any:
        cached = self._function_cache.get(blob_id)
        if cached is not None:
            return cached
        pickled = self.io.run(self.gcs.call("kv_get", {"ns": "functions", "key": blob_id}))
        if pickled is None:
            raise exc.RayTpuError(f"function blob {blob_id} not found in GCS")
        func = cloudpickle.loads(pickled)
        self._function_cache[blob_id] = func
        return func

    # ------------------------------------------------------- arg resolution
    def _pack_args(self, args: tuple, kwargs: dict) -> Tuple[List[TaskArg], List[ObjectID]]:
        packed: List[TaskArg] = []
        dep_ids: List[ObjectID] = []
        flat = list(args) + [("__kw__", k, v) for k, v in (kwargs or {}).items()]
        for item in flat:
            actual = item[2] if isinstance(item, tuple) and len(item) == 3 and item[0] == "__kw__" else item
            kw = item[1] if actual is not item else None
            if isinstance(actual, ObjectRef):
                # Inline small owned values the owner already holds
                # (ref: transport/dependency_resolver.h inlines small
                # in-memory objects): the consuming worker skips the
                # whole dependency wait. Error payloads stay by-ref so
                # the dependency failure surfaces as a task error, not
                # as a (err, tb) tuple argument.
                inline = self.memory_store.get(actual.id())
                if (inline is not None and len(inline) <= _SMALL
                        and ser.get_metadata(inline) == ser.META_PLAIN):
                    packed.append(TaskArg(ArgKind.VALUE,
                                          value=(kw, inline)))
                    continue
                packed.append(TaskArg(
                    ArgKind.OBJECT_REF, value=kw, object_id=actual.id(),
                    owner=actual.owner_address or self.address))
                dep_ids.append(actual.id())
                self._pin_task_dep(actual.id())
            else:
                data = ser.serialize(actual)
                if len(data) > _SMALL:
                    ref = self.put(actual)
                    packed.append(TaskArg(
                        ArgKind.OBJECT_REF, value=kw, object_id=ref.id(),
                        owner=self.address))
                    dep_ids.append(ref.id())
                    self._pin_task_dep(ref.id())
                else:
                    packed.append(TaskArg(ArgKind.VALUE, value=(kw, data)))
        return packed, dep_ids

    @staticmethod
    def _resolve_strategy(opts: dict):
        """scheduling_strategy option, with the `placement_group=` and
        `accelerator_type=` shorthands folded in (ref:
        ray_option_utils.py option groups; accelerator_type maps to a
        hard node-label match like the reference's
        accelerator-type-to-label resolution)."""
        strategy = opts.get("scheduling_strategy")
        pg = opts.get("placement_group")
        acc = opts.get("accelerator_type")
        if sum(x is not None for x in (strategy, pg, acc)) > 1:
            raise ValueError(
                "scheduling_strategy=, placement_group= and "
                "accelerator_type= are mutually exclusive")
        if strategy is not None:
            return strategy
        if pg is not None:
            return PlacementGroupSchedulingStrategy(
                placement_group_id=getattr(pg, "id", pg),
                placement_group_bundle_index=opts.get(
                    "placement_group_bundle_index", -1))
        if acc is not None:
            from ..util.scheduling_strategies import (
                In, NodeLabelSchedulingStrategy)

            return NodeLabelSchedulingStrategy(
                hard={"accelerator_type": In(str(acc))})
        return DefaultSchedulingStrategy()

    @staticmethod
    def _build_resources(opts: dict) -> ResourceSet:
        res = dict(opts.get("resources") or {})
        if opts.get("num_cpus") is not None:
            res["CPU"] = opts["num_cpus"]
        elif "CPU" not in res:
            res["CPU"] = 1
        if opts.get("num_tpus"):
            res["TPU"] = opts["num_tpus"]
        return ResourceSet(res)

    # ------------------------------------------------------ normal tasks
    def _prepare_runtime_env(self, opts: dict,
                             allow_container: bool = True) -> Optional[dict]:
        """Pack a runtime_env option for the wire (ref: runtime envs,
        SURVEY §2.2). Cached per (env-spec, content fingerprint):
        re-tarring a working_dir on every one of thousands of
        submissions would dominate the submit path. The fingerprint is
        a shallow walk of every file's (relpath, size, mtime) — editing
        a file's CONTENTS bumps its mtime, so re-submitting from the
        same driver ships fresh code (the reference re-hashes directory
        contents per upload; a directory-level mtime would miss edits
        inside existing files)."""
        env = opts.get("runtime_env")
        if not env:
            return None
        if not allow_container and isinstance(env, dict) \
                and env.get("container"):
            # the per-task-body container model cannot seal a long-lived
            # actor or a streaming generator — reject LOUDLY at
            # submission instead of silently running on the host
            raise ValueError(
                "container runtime_env supports plain tasks only; "
                "actors and streaming generators run on the host "
                "worker (use pip/conda/working_dir envs for those)")
        import json
        import os as _os

        def _dir_fingerprint(d: str):
            if not d:
                return 0.0
            sig = []
            try:
                for root, subdirs, files in _os.walk(d):
                    subdirs.sort()
                    for f in sorted(files):
                        p = _os.path.join(root, f)
                        try:
                            st = _os.stat(p)
                        except OSError:
                            continue
                        sig.append((_os.path.relpath(p, d),
                                    st.st_size, st.st_mtime))
            except OSError:
                return 0.0
            return tuple(sig)

        dirs = [env.get("working_dir") or ""] + list(
            env.get("py_modules") or [])
        try:
            mtimes = tuple(_dir_fingerprint(d) for d in dirs)
        except OSError:
            mtimes = ()
        try:
            cache_key = (json.dumps(env, sort_keys=True, default=str),
                         mtimes)
        except TypeError:
            cache_key = None
        if cache_key is not None and cache_key in self._runtime_env_cache:
            return self._runtime_env_cache[cache_key]  # may be None
        from .runtime_env import prepare_runtime_env

        wire = prepare_runtime_env(self, env)
        if cache_key is not None:
            self._runtime_env_cache[cache_key] = wire
        return wire

    def submit_task(self, func: Any, args: tuple, kwargs: dict, opts: dict):
        clock = (_StageClock(_stage_hist())
                 if self.cfg.submit_stage_timers_enabled else None)
        # validate options BEFORE packing args: _pack_args pins dependencies
        # that are only released through the submit coroutine's finally
        strategy = self._resolve_strategy(opts)
        if opts.get("speculation", "") not in ("", "auto", "off"):
            raise ValueError(
                f"speculation must be 'auto' or 'off', got "
                f"{opts.get('speculation')!r}")
        descriptor = self.export_function(func)
        if clock:
            clock.mark("export_fn")
        packed, deps = self._pack_args(args, kwargs)
        if clock:
            clock.mark("serialize")
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        spec = TaskSpec(
            task_id=TaskID.for_normal_task(self.job_id),
            job_id=self.job_id,
            function=descriptor,
            args=packed,
            num_returns=0 if streaming else num_returns,
            resources=self._build_resources(opts),
            scheduling_strategy=strategy,
            # streaming tasks never auto-retry: a replay would re-emit items
            # the consumer already saw (the failure rides the stream instead)
            max_retries=0 if streaming else opts.get(
                "max_retries", self.cfg.task_max_retries_default),
            retry_exceptions=opts.get("retry_exceptions", False),
            streaming=streaming,
            backpressure_items=opts.get(
                "generator_backpressure_num_objects", 0) or 0,
            owner_address=self.address,
            runtime_env=self._prepare_runtime_env(
                opts, allow_container=not streaming),
            idempotent=bool(opts.get("idempotent", False)),
            speculation=opts.get("speculation", "") or "",
        )
        from ..util.tracing import inject_trace_ctx

        inject_trace_ctx(spec)
        if clock:
            clock.mark("spec_mint")
        # registered before the submit coroutine runs, so an immediate
        # cancel() cannot race past the bookkeeping
        self._inflight[spec.task_id] = {"canceled": False, "worker_address": None}
        if self.cfg.lineage_pinning_enabled and not streaming:
            self._lineage[spec.task_id] = spec
        if clock:
            clock.mark("bookkeeping")
        submit_t = time.time()
        self._record_transition(spec.task_id, "SUBMITTED", ts=submit_t,
                                name=spec.function.repr_name,
                                state="SUBMITTED", start_time=submit_t)
        if clock:
            clock.mark("task_event")
        if streaming:
            self._streams[spec.task_id] = _StreamState()
            self.io.spawn(self._submit_normal(spec, deps))
            if clock:
                clock.mark("dispatch")
                clock.total()
            return ObjectRefGenerator(spec.task_id, self)
        refs = [ObjectRef(oid, self.address) for oid in spec.return_ids()]
        if self._lane_eligible(spec, deps) and self._lane_submit(spec):
            if clock:
                clock.mark("dispatch")
                clock.total()
            return refs
        self.io.spawn(self._submit_normal(spec, deps))
        if clock:
            clock.mark("dispatch")
            clock.total()
        return refs

    def _lane_eligible(self, spec: TaskSpec, deps: List[ObjectID]) -> bool:
        """Fast-lane tasks: default-shaped, dependency-free, one return.
        Everything else — including hedge-eligible tasks, whose backup
        copy management lives on the asyncio control plane — takes the
        normal submit path."""
        return (self._lane_pool is not None
                and not self._hedge_eligible(spec)
                and not deps
                and spec.num_returns == 1
                and spec.runtime_env is None
                and isinstance(spec.scheduling_strategy,
                               DefaultSchedulingStrategy)
                and spec.resources.key() == (("CPU", 1.0),))

    def _lane_submit(self, spec: TaskSpec) -> bool:
        event = threading.Event()
        oid = ObjectID.for_return(spec.task_id, 1)
        self._lane_events[oid] = event
        if self._lane_pool.try_submit(spec, event):
            return True
        self._lane_events.pop(oid, None)
        return False

    async def _submit_normal(self, spec: TaskSpec, deps: List[ObjectID]):
        info = self._inflight.setdefault(spec.task_id, {
            "canceled": False, "worker_address": None})
        try:
            attempts = spec.max_retries + 1
            last_error: Optional[BaseException] = None
            for attempt in range(attempts):
                if info["canceled"]:
                    raise exc.TaskCancelledError(
                        f"task {spec.function.repr_name} was cancelled")
                try:
                    app_errored = await self._run_on_leased_worker(spec, info)
                    last_error = None
                    break
                except (ConnectionLost, exc.WorkerCrashedError) as e:
                    if info["canceled"]:
                        # the lease loss is incidental — the user asked
                        # for cancellation; don't chain the crash noise
                        raise exc.TaskCancelledError(
                            f"task {spec.function.repr_name} was "
                            "cancelled") from None
                    last_error = e
                    await asyncio.sleep(0.02 * (2 ** attempt))
            if last_error is not None:
                self._store_error(spec, exc.WorkerCrashedError(
                    f"task {spec.function.repr_name} failed after {attempts} attempts: {last_error}"))
                self._record_transition(spec.task_id, "FAILED",
                                        state="FAILED",
                                        end_time=time.time(),
                                        error=str(last_error))
            else:
                # a task whose body raised is FAILED in the state API even
                # though submission completed cleanly (its returns hold the
                # serialized error)
                terminal = "FAILED" if app_errored else "FINISHED"
                self._record_transition(
                    spec.task_id, terminal,
                    state=terminal,
                    end_time=time.time(),
                    error="application error" if app_errored else None)
        except BaseException as e:  # noqa: BLE001
            self._store_error(spec, e)
            self._record_transition(spec.task_id, "FAILED", state="FAILED",
                                    end_time=time.time(), error=str(e))
        finally:
            self._inflight.pop(spec.task_id, None)
            for oid in deps:
                self._unpin_task_dep(oid)

    def _store_error(self, spec: TaskSpec, error: BaseException):
        data = ser.serialize_error(error)
        if spec.streaming:
            # submission-level failure becomes the next (final) stream item
            state = self._streams.get(spec.task_id)
            if state is not None:
                index = state.received + 1
                oid = ObjectID.for_return(spec.task_id, index)
                self.memory_store.put(oid, data)
                state.queue.put_nowait(ObjectRef(oid, self.address))
                state.queue.put_nowait(_STREAM_DONE)
            return
        for oid in spec.return_ids():
            self.memory_store.put(oid, data)
            try:
                self.store.put(oid, data)
                self.io.spawn(self._notify_sealed(oid, len(data)))
            except OSError:
                pass  # store already destroyed (shutdown race)

    async def _run_on_leased_worker(self, spec: TaskSpec, info: Optional[dict] = None):
        if self._hedge_eligible(spec):
            return await self._run_hedged(spec, info)
        return await self._run_attempt(spec, info)

    # ------------------------------------------- hedged speculative execution
    # (The Tail at Scale: issue a backup copy of a slow idempotent task on
    #  a different node, first reply wins, loser is cancelled)
    def _hedge_eligible(self, spec: TaskSpec) -> bool:
        return (self.cfg.task_speculation_enabled
                and spec.idempotent
                and spec.speculation != "off"
                and not spec.streaming
                and spec.actor_id is None
                and not spec.actor_creation)

    def _hedge_delay(self, spec: TaskSpec) -> Optional[float]:
        """Owner-side hedge trigger delay: the per-fn latency profile
        (EMA of past push->reply durations) times the hedge factor. None
        when no profile exists yet — then only a raylet watchdog
        hedge_hint triggers the backup."""
        ema = self._hedge_ema.get(spec.function.repr_name)
        if ema is None:
            return None
        return max(self.cfg.task_hedge_min_delay_s,
                   ema * self.cfg.task_hedge_ema_factor)

    async def _run_hedged(self, spec: TaskSpec, info: Optional[dict]):
        state = {"published": False, "publishes": 0}
        hint = asyncio.Event()
        self._hedge_hints[spec.task_id.hex()] = hint
        hedge: Optional[asyncio.Future] = None
        primary = asyncio.ensure_future(
            self._run_attempt(spec, info, publish_state=state,
                              role="primary"))
        try:
            hint_task = asyncio.ensure_future(hint.wait())
            try:
                await asyncio.wait({primary, hint_task},
                                   timeout=self._hedge_delay(spec),
                                   return_when=asyncio.FIRST_COMPLETED)
            finally:
                # a bare Event.wait() holds no resource: safe to cancel
                if not hint_task.done():
                    hint_task.cancel()
            if primary.done() or (info is not None and info["canceled"]):
                return await primary
            _hedge_counter("task_hedges_launched").inc()
            hedge = asyncio.ensure_future(
                self._run_attempt(spec, info, publish_state=state,
                                  avoid_node=state.get("primary_node"),
                                  role="hedge"))
            # first reply to publish wins (an attempt that aborted because
            # the other copy sealed returns None); an attempt dying with an
            # infra error (ConnectionLost/WorkerCrashed) defers to the
            # other copy, and only if BOTH fail does the error escape into
            # _submit_normal's retry loop
            pending = {primary, hedge}
            winner: Optional[asyncio.Future] = None
            first_exc: Optional[BaseException] = None
            while pending and winner is None:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for fut in done:
                    if fut.exception() is not None:
                        if first_exc is None:
                            first_exc = fut.exception()
                    # fut came out of asyncio.wait's done set: result()
                    # is an immediate read, not a blocking future wait
                    elif fut.result() is not None:  # graftlint: ignore[blocking]
                        winner = fut
                        break
            if winner is None:
                if first_exc is not None:
                    raise first_exc
                raise exc.RayTpuError(
                    f"hedged task {spec.function.repr_name}: no attempt "
                    "published a result")
            loser = hedge if winner is primary else primary
            if winner is hedge:
                _hedge_counter("task_hedges_won").inc()
                self._report_primary_straggler(spec, state)
            if not loser.done():
                background(self._finalize_hedge_loser(
                    spec, loser,
                    state.get("hedge_addr" if winner is primary
                              else "primary_addr")))
            else:
                loser.exception()  # retrieved: both replies are in
            return winner.result()
        finally:
            self._hedge_hints.pop(spec.task_id.hex(), None)

    async def _finalize_hedge_loser(self, spec: TaskSpec,
                                    loser: asyncio.Future,
                                    address: Optional[str]):
        """Cancel the losing copy through the normal cancel_task path and
        drain its attempt coroutine (which skips publication — the winner
        already sealed — and releases its own lease)."""
        if address:
            try:
                client = await self._client_for(address)
                await client.call("cancel_task", {
                    "task_id": spec.task_id, "force": False}, timeout=5)
                _hedge_counter("task_hedges_cancelled").inc()
            except (asyncio.TimeoutError, ConnectionLost, RpcError, OSError):
                pass  # loser's worker already gone — nothing to cancel
        try:
            await loser
        except (exc.RayTpuError, ConnectionLost, RpcError,
                asyncio.TimeoutError, OSError):
            pass  # loser infra errors are moot once the winner published

    def _report_primary_straggler(self, spec: TaskSpec, state: dict) -> None:
        """A won hedge is a measured straggle of the primary's node: feed
        it into the GCS straggler stats so scheduling deprioritization
        sees task-plane stragglers, not just collective skew."""
        node = state.get("primary_node")
        push_t = state.get("primary_push_t")
        if not node or push_t is None:
            return
        ema = self._hedge_ema.get(spec.function.repr_name) or 0.0
        late = max(0.0, time.monotonic() - push_t - ema)
        background(self.gcs.call("report_straggler", {
            "node_id": node, "late_s": late,
            "source": "task_hedge"}, timeout=self.cfg.gcs_rpc_timeout_s or None))

    async def _run_attempt(self, spec: TaskSpec, info: Optional[dict] = None,
                           publish_state: Optional[dict] = None,
                           avoid_node: Optional[str] = None,
                           role: str = "primary"):
        sched_class = spec.scheduling_class()
        pool = self._lease_pools.setdefault(sched_class, _LeasePool())
        self._record_transition(spec.task_id, "PENDING_NODE_ASSIGNMENT")
        # lease-queue stage: async-side (pool pop or raylet round trip +
        # spillback chain), so it reports alongside — not inside — the
        # synchronous submit partition
        timed = self.cfg.submit_stage_timers_enabled
        t_lease = time.perf_counter() if timed else 0.0
        grant = await self._acquire_lease(pool, spec, avoid_node=avoid_node)
        if timed:
            _stage_hist().observe(time.perf_counter() - t_lease,
                                  tags={"stage": "lease_acquire"})
        keep = False
        try:
            if publish_state is not None and publish_state["published"]:
                # the other copy won while this lease was in flight:
                # never cancel mid-acquisition (rid-deduped grants would
                # leak) — take the grant, skip the push, return it clean
                keep = True
                return None
            if info is not None:
                if info["canceled"]:
                    keep = True  # lease unused; return it to the pool clean
                    raise exc.TaskCancelledError(
                        f"task {spec.function.repr_name} was cancelled")
                info["worker_address"] = grant["worker_address"]
            if grant.get("chip_ids"):
                spec.chip_ids = grant["chip_ids"]
            gnode_id = grant.get("node_id")
            gworker = grant.get("worker_id")
            if publish_state is not None:
                publish_state[f"{role}_node"] = (
                    gnode_id.hex() if gnode_id else "")
                publish_state[f"{role}_addr"] = grant["worker_address"]
                publish_state[f"{role}_push_t"] = time.monotonic()
            self._record_transition(
                spec.task_id, "SUBMITTED_TO_WORKER",
                node_id=gnode_id.hex() if gnode_id else "",
                worker_id=gworker.hex() if gworker else "")
            client = await self._client_for(grant["worker_address"])
            t_push = time.monotonic()
            # the reply arrives when the task finishes — unbounded by
            # design (tasks may run for hours); the stall sentinel and
            # hedging bound the wait instead of a wire timeout
            reply = await client.call(  # graftlint: ignore[rpc-timeout]
                "push_task", cloudpickle.dumps(spec))
            if publish_state is not None:
                if publish_state["published"]:
                    keep = True  # loser replied after the winner: discard
                    return None
                publish_state["published"] = True
                publish_state["publishes"] += 1
                if publish_state["publishes"] > 1:  # defensive: must stay 0
                    _hedge_counter("task_hedge_duplicate_publishes").inc()
            gnode = grant.get("node_id")
            errored = self._handle_task_reply(
                spec, reply, node_id=gnode.hex() if gnode else "")
            if self.cfg.task_speculation_enabled and not errored:
                fn = spec.function.repr_name
                dur = time.monotonic() - t_push
                prev = self._hedge_ema.get(fn)
                self._hedge_ema[fn] = (dur if prev is None
                                       else 0.8 * prev + 0.2 * dur)
            keep = True
            return errored
        finally:
            await self._release_lease(pool, grant, spec, reusable=keep)

    async def _acquire_lease(self, pool: _LeasePool, spec: TaskSpec,
                             avoid_node: Optional[str] = None) -> dict:
        while True:
            if pool.idle:
                if avoid_node is None:
                    return pool.idle.pop()
                # hedge attempts must land off the primary's node: take the
                # first idle grant elsewhere, else fall through to a fresh
                # lease request carrying avoid_nodes
                for i, g in enumerate(pool.idle):
                    gnode = g.get("node_id")
                    if (gnode.hex() if gnode else "") != avoid_node:
                        return pool.idle.pop(i)
            if pool.in_flight < self.cfg.max_pending_lease_requests_per_scheduling_class:
                pool.in_flight += 1
                try:
                    return await self._request_lease(spec, avoid_node=avoid_node)
                finally:
                    pool.in_flight -= 1
                    # the freed request slot must wake a queued submission:
                    # an actor-creation grant is pinned for life and never
                    # passes through _release_lease, so without this wake
                    # the 11th+ queued creation in a scheduling class waits
                    # forever (envelope: 1k actors of one class)
                    pool.wake_one()
            # saturated: wait for a slot, then retry the whole acquisition
            fut = asyncio.get_event_loop().create_future()
            pool.waiters.append(fut)
            await fut

    async def _request_lease(self, spec: TaskSpec,
                             avoid_node: Optional[str] = None) -> dict:
        import uuid

        payload = {
            "resources": spec.resources.to_dict(),
            "strategy": spec.scheduling_strategy,
            "owner_address": self.address,
            "actor_id": spec.actor_id if spec.actor_creation else None,
            "task_id": spec.task_id,
            # lane leases are preemptible-when-idle (reclaim_lease push)
            "lane": spec.function.repr_name == "__lane__",
            # stable across retries: the raylet dedups grants by this id, so
            # a lost reply cannot leak a second worker lease
            "request_id": uuid.uuid4().hex,
        }
        if avoid_node:
            # hedge placement: the serving raylet excludes these nodes
            # when picking (spilling elsewhere if the local node is one)
            payload["avoid_nodes"] = [avoid_node]
        info = self._inflight.get(spec.task_id)
        strategy = spec.scheduling_strategy
        pg_strategy = (isinstance(strategy, PlacementGroupSchedulingStrategy)
                       and strategy.placement_group_id is not None)
        # locality-aware leasing (DEFAULT strategy only — explicit
        # strategies encode the user's placement intent): start the lease
        # chain at the node holding the task's argument bytes; its raylet
        # still applies the hybrid policy and may spill back out
        locality_raylet = None
        from .task_spec import DefaultSchedulingStrategy

        if (strategy is None
                or isinstance(strategy, DefaultSchedulingStrategy)) and spec.args:
            target = self._locality_node(spec)
            if target is not None and target != self.node_id.hex():
                addr = await self._node_raylet_address(target)
                if addr:
                    try:
                        locality_raylet = await self._raylet_client_for(addr)
                    except Exception:
                        locality_raylet = None
        for pg_attempt in range(8):
            raylet = locality_raylet or self.raylet
            if pg_strategy:
                address = await self._pg_bundle_address(strategy)
                raylet = await self._raylet_client_for(address)
            # a fresh attempt gets a fresh spillback budget — no_spill
            # sticking from a previous attempt's chain cap would pin the
            # lease to a saturated raylet forever
            payload.pop("no_spill", None)
            try:
                for hop in range(16):  # bounded spillback chain
                    if info is not None:
                        # remembered so cancel() can reach the raylet
                        # currently queueing this lease request
                        info["lease_raylet"] = raylet
                    if hop == 15:
                        # mutually-stale availability views can bounce a
                        # lease between saturated raylets; pin it to the
                        # current raylet's queue instead of erroring (it
                        # waits exactly as it would have pre-spillback)
                        payload["no_spill"] = True
                    reply = await self._lease_call(raylet, payload)
                    if reply.get("granted"):
                        reply["_raylet"] = raylet
                        return reply
                    node_id, address = reply["retry_at"]
                    raylet = await self._raylet_client_for(address)
                raise exc.RayTpuError("lease spillback chain too long")
            except (ValueError, ConnectionLost):
                # the bundle moved (node died, PG rescheduling) between the
                # directory lookup and the lease request — re-resolve
                if not pg_strategy:
                    if locality_raylet is not None:
                        # the locality hint pointed at a dead/stale node:
                        # degrade to the local raylet, don't fail the task
                        locality_raylet = None
                        continue
                    raise
                self._pg_cache.pop(strategy.placement_group_id, None)
                await asyncio.sleep(0.05 * (pg_attempt + 1))
        raise exc.RayTpuError(
            f"could not lease into placement group "
            f"{strategy.placement_group_id} (bundle unavailable)")

    async def _lease_call(self, raylet: RpcClient, payload: dict):
        """One lease RPC. With `lease_rpc_timeout_s` set (chaos tests,
        unreliable transports), lost frames time out and retry; the
        request_id makes retries idempotent at the raylet."""
        per_try = self.cfg.lease_rpc_timeout_s
        if per_try <= 0:
            return await raylet.call("request_worker_lease", payload)
        last: Optional[BaseException] = None
        for _ in range(10):
            try:
                return await raylet.call("request_worker_lease", payload,
                                         timeout=per_try)
            except asyncio.TimeoutError as e:
                last = e
                # a queued lease legitimately takes as long as the cluster
                # is busy — escalate the per-try window so retries (cheap,
                # deduped) only fire fast when loss is likely
                per_try = min(per_try * 2, 60.0)
        raise exc.RayTpuError(
            f"lease request timed out after retries: {last}")

    async def _pg_bundle_address(self, strategy) -> str:
        """Resolve the raylet address of the bundle the lease targets,
        blocking until the PG is reserved (this is what makes `pg.ready()` —
        a trivial task scheduled into the PG — resolve exactly when the
        reservation lands, matching the reference's
        bundle_reservation_check_func trick)."""
        nodes = self._pg_cache.get(strategy.placement_group_id)
        if nodes is None:
            reply = await self.gcs.call("wait_placement_group_ready", {
                "pg_id": strategy.placement_group_id})
            if reply["status"] != "ready":
                raise exc.RayTpuError(
                    f"placement group {strategy.placement_group_id} was removed")
            nodes = reply["bundle_nodes"]
            # cached so steady-state submissions skip the GCS hop; the lease
            # retry path invalidates on ValueError/ConnectionLost
            self._pg_cache[strategy.placement_group_id] = nodes
        index = strategy.placement_group_bundle_index
        if index >= 0:
            if index >= len(nodes):
                raise ValueError(
                    f"bundle index {index} out of range ({len(nodes)} bundles)")
            return nodes[index][1]
        self._pg_rr += 1
        return nodes[self._pg_rr % len(nodes)][1]

    async def _release_lease(self, pool: _LeasePool, grant: dict, spec: TaskSpec,
                             reusable: bool):
        if not spec.actor_creation:
            if reusable and pool.waiters:
                pool.idle.append(grant)  # hand the leased worker to the backlog
            else:
                raylet = grant.get("_raylet", self.raylet)
                try:
                    await raylet.call("return_worker", {
                        "lease_id": grant["lease_id"],
                        "disconnect_worker": not reusable,
                    })
                except Exception:
                    pass
        # always wake one waiter — even on the failure path, so queued
        # submissions retry instead of stranding
        pool.wake_one()

    _raylet_clients: Dict[str, RpcClient]

    async def _raylet_client_for(self, address: str) -> RpcClient:
        if not hasattr(self, "_raylet_clients_map"):
            self._raylet_clients_map = {}
        client = self._raylet_clients_map.get(address)
        if client is None or client.closed:
            client = RpcClient(address)
            await client.connect()
            self._raylet_clients_map[address] = client
        return client

    async def _client_for(self, address: str) -> RpcClient:
        """One connection per peer. The connect task is cached synchronously so
        concurrent callers share a single connection — per-caller actor task
        ordering relies on all pushes riding one ordered stream."""
        task = self._worker_clients.get(address)
        if task is not None:
            client = await asyncio.shield(task)
            if not client.closed:
                return client
            self._worker_clients.pop(address, None)

        async def _make():
            client = RpcClient(address)
            # streaming tasks report items as PUSH frames on this connection
            client.on_push("generator_item", self._on_generator_item)
            # target workers are already registered (their server is up), so a
            # dead socket means death, not startup: fail fast so in-flight
            # actor calls surface ActorDiedError promptly instead of burning
            # the whole startup window re-dialing a corpse
            await client.connect(timeout=self.cfg.worker_dial_timeout_s)
            return client

        task = asyncio.ensure_future(_make())
        self._worker_clients[address] = task
        try:
            return await asyncio.shield(task)
        except BaseException:
            if self._worker_clients.get(address) is task:
                self._worker_clients.pop(address, None)
            raise

    def _handle_task_reply(self, spec: TaskSpec, reply: dict,
                           node_id: str = "") -> bool:
        """reply: {results: [(oid, data|None)], error: bytes|None,
        sealed?: [(oid, size)]}. Returns True when the task raised (its
        returns hold the error)."""
        if reply.get("error") is not None:
            for oid in spec.return_ids():
                self.memory_store.put(oid, reply["error"])
            return True
        for oid, data in reply["results"]:
            if data is not None:
                self.memory_store.put(oid, data)
            # else: large result sealed in plasma by the executor
        if node_id:
            for oid, size in reply.get("sealed", ()):
                self._note_locality(oid, node_id, size)
        return False

    # ------------------------------------------------ locality-aware leasing
    _LOCALITY_CAP = 65536  # hint entries kept (FIFO)

    def _note_locality(self, oid: ObjectID, node_hex: str, size: int) -> None:
        loc = self._obj_locality
        loc[oid] = (node_hex, size)
        loc.move_to_end(oid)
        while len(loc) > self._LOCALITY_CAP:
            loc.popitem(last=False)

    def _locality_node(self, spec: TaskSpec) -> Optional[str]:
        """Node holding the most known dependency bytes, when that beats
        the threshold (ref: LocalityAwareLeasePolicy::GetBestNodeForTask)."""
        if self.cfg.scheduler_locality_min_bytes <= 0:
            return None
        by_node: Dict[str, int] = {}
        for arg in spec.args:
            if arg.object_id is None:
                continue
            hint = self._obj_locality.get(arg.object_id)
            if hint is not None:
                by_node[hint[0]] = by_node.get(hint[0], 0) + hint[1]
        if not by_node:
            return None
        best = max(by_node, key=by_node.get)
        if by_node[best] < self.cfg.scheduler_locality_min_bytes:
            return None
        return best

    async def _node_raylet_address(self, node_hex: str) -> Optional[str]:
        """node_id -> raylet address, via a TTL-cached GCS node listing
        (locality leases are for big-data tasks; one listing per 10 s is
        noise next to the transfers it avoids)."""
        now = time.monotonic()
        # staleness alone gates the refresh: a hint pointing at a dead
        # node must NOT turn every submission into a GCS listing — a
        # fresh-cache miss just skips the locality lease this time
        if now - self._node_addr_ts > 10.0:
            try:
                infos = await self.gcs.call("get_all_nodes", {})
            except Exception:
                return None
            self._node_addr_cache = {
                i.node_id.hex(): i.address for i in infos if i.alive}
            self._node_addr_ts = now
        return self._node_addr_cache.get(node_hex)

    # ------------------------------------------------- streaming generators
    def _on_generator_item(self, payload):
        """PUSH from the executing worker: one yielded object, or the end
        marker (ref: _raylet.pyx streaming_generator_returns). Runs on the
        io loop inside the client recv loop."""
        state = self._streams.get(payload["task_id"])
        if state is None:
            return
        if payload.get("worker_address"):
            state.worker_address = payload["worker_address"]
        if payload.get("done"):
            state.total = payload.get("total", 0)
            state.queue.put_nowait(_STREAM_DONE)
            return
        oid = payload["object_id"]
        data = payload.get("data")
        if data is not None:
            self.memory_store.put(oid, data)
        self._owned_in_plasma.add(oid)
        state.received += 1
        state.queue.put_nowait(ObjectRef(oid, self.address))

    def next_stream_item(self, task_id: TaskID,
                         timeout: Optional[float]) -> Optional[ObjectRef]:
        """Block for the next yielded ObjectRef; None = stream exhausted."""
        return self.io.run(self._next_stream_item(task_id), timeout)

    async def _next_stream_item(self, task_id: TaskID) -> Optional[ObjectRef]:
        state = self._streams.get(task_id)
        if state is None:
            return None
        item = await state.queue.get()
        if item is _STREAM_DONE:
            self._streams.pop(task_id, None)
            return None
        state.consumed += 1
        if state.worker_address:
            background(self._send_stream_ack(task_id, state))
        return item

    async def _send_stream_ack(self, task_id: TaskID, state: _StreamState):
        """Consumption ack driving producer backpressure (the
        generator_waiter.h role)."""
        try:
            client = await self._client_for(state.worker_address)
            await client.call("generator_ack", {
                "task_id": task_id, "consumed": state.consumed})
        except Exception:
            pass  # producer gone (stream finished/worker died) — no ack needed

    def stream_completed(self, task_id: TaskID) -> bool:
        state = self._streams.get(task_id)
        return state is None or (state.total is not None
                                 and state.consumed >= state.total)

    def release_stream(self, task_id: TaskID) -> None:
        self._streams.pop(task_id, None)

    # ------------------------------------------------------------ cancel
    def cancel(self, ref_or_gen, force: bool = False) -> None:
        """Cancel an in-flight normal task (ref: core_worker.cc CancelTask,
        _raylet.pyx cancel paths). Queued tasks are dropped before dispatch;
        running tasks get TaskCancelledError raised in their executing
        thread; force kills the worker process."""
        if isinstance(ref_or_gen, ObjectRefGenerator):
            task_id = ref_or_gen.task_id
        else:
            task_id = ref_or_gen.id().task_id()
        self.io.run(self._cancel(task_id, force))

    async def _cancel(self, task_id: TaskID, force: bool):
        info = self._inflight.get(task_id)
        if info is None:
            return  # already finished (or not a task this worker submitted)
        info["canceled"] = True
        address = info.get("worker_address")
        if address:
            try:
                client = await self._client_for(address)
                await client.call("cancel_task", {
                    "task_id": task_id, "force": force}, timeout=5)
            except Exception:
                pass  # worker already gone — the retry loop sees `canceled`
            # lane tasks dispatched into a ring may sit behind long
            # tasks on the lane's serial worker: finalize promptly
            # owner-side (the worker's eventual skip-reply is dropped)
            if self._lane_pool is not None:
                self._lane_pool.cancel_pending(task_id)
        else:
            # queued on the fast-lane feeder: fail it immediately (a
            # dispatch-time check alone could be a full task-runtime
            # away when the lane window is occupied)
            if self._lane_pool is not None and \
                    self._lane_pool.cancel_queued(task_id):
                return
            # no worker yet: the lease request may be queued at a raylet
            # behind resources that never free — fail it there so the submit
            # coroutine wakes up (ref: node_manager CancelWorkerLease)
            raylet = info.get("lease_raylet") or self.raylet
            try:
                await raylet.call("cancel_lease_request",
                                  {"task_id": task_id}, timeout=5)
            except Exception:
                pass
            # fast-lane window: the task may still DISPATCH right after
            # this cancel (feeder re-checks the flag, but a ring push
            # already in flight sets worker_address moments later).
            # Chase it: deliver the cancel once an address appears.
            self.io.spawn(self._chase_cancel(task_id, force))

    async def _chase_cancel(self, task_id: TaskID, force: bool):
        for _ in range(50):
            await asyncio.sleep(0.1)
            info = self._inflight.get(task_id)
            if info is None:
                return  # finished or errored meanwhile
            address = info.get("worker_address")
            if address:
                try:
                    client = await self._client_for(address)
                    await client.call("cancel_task", {
                        "task_id": task_id, "force": force}, timeout=5)
                except Exception:
                    pass
                if self._lane_pool is not None:
                    self._lane_pool.cancel_pending(task_id)
                return

    # ------------------------------------------------------------- actors
    def submit_actor_creation(self, cls: Any, args: tuple, kwargs: dict, opts: dict) -> ActorID:
        # all option validation BEFORE any state mutation/arg pinning
        strategy = self._resolve_strategy(opts)
        detached = opts.get("lifetime") == "detached"
        if detached and not opts.get("name"):
            raise ValueError("detached actors must be named (lookup is the "
                             "only way to reach them after the driver exits)")
        actor_id = ActorID.of(self.job_id)
        descriptor = self.export_function(cls)
        packed, deps = self._pack_args(args, kwargs)
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(actor_id),
            job_id=self.job_id,
            function=descriptor,
            args=packed,
            num_returns=0,
            resources=self._build_resources(opts),
            scheduling_strategy=strategy,
            actor_id=actor_id,
            actor_creation=True,
            actor_max_restarts=opts.get("max_restarts", self.cfg.actor_max_restarts_default),
            # 0 = unset: sync actors default to 1 thread, async actors to
            # 1000 slots; an EXPLICIT max_concurrency=1 stays serialized
            actor_max_concurrency=opts.get("max_concurrency") or 0,
            actor_name=opts.get("name") or "",
            owner_address=self.address,
            runtime_env=self._prepare_runtime_env(
                opts, allow_container=False),
        )
        state = _ActorState(actor_id=actor_id)
        state.creation_spec = spec
        state.owned = True
        self._actors[actor_id] = state
        register_payload = {
            "actor_id": actor_id,
            "name": spec.actor_name,
            "namespace": opts.get("namespace", ""),
            "detached": detached,
            "owner_is_driver": self.mode == "driver",
            "class_name": spec.function.repr_name,
            "max_restarts": spec.actor_max_restarts,
            "creation_spec": cloudpickle.dumps(spec),
            # register + keyed lifecycle subscription in ONE GCS hop
            # (the subscription is installed server-side before the
            # registered state publishes, so no transition is missed)
            "subscribe": True,
        }
        # restartable actors keep creation args pinned for their lifetime so
        # the creation spec can be resubmitted
        pinned_deps = [] if spec.actor_max_restarts > 0 else deps
        if spec.actor_name:
            # named: registration stays synchronous so a duplicate-name
            # ValueError surfaces at .remote() itself
            self.io.run(self.gcs.call("register_actor", register_payload))
            self._subscribed_channels.add("actor:" + actor_id.hex())
            self.io.spawn(self._submit_actor_creation(spec, pinned_deps))
        else:
            # unnamed: the whole register->lease->push chain runs async,
            # so creations PIPELINE — .remote() costs no GCS round trip
            # (the r4 envelope measured 90-183 ms/actor, nearly all of
            # it these two blocking hops queued behind a busy GCS; ref
            # gcs_actor_manager.cc:394 RegisterActor is async there too)
            self.io.spawn(self._register_and_create(
                spec, register_payload, pinned_deps))
        return actor_id

    async def _register_and_create(self, spec: TaskSpec, payload: dict,
                                   deps: List[ObjectID]):
        try:
            await self.gcs.call("register_actor", payload)
        except asyncio.CancelledError:
            raise  # loop teardown — not a registration verdict
        except Exception as e:
            state = self._actors.get(spec.actor_id)
            if state is not None:
                state.state = "DEAD"
                state.death_cause = f"actor registration failed: {e!r}"
                for fut in state.waiters:
                    if not fut.done():
                        fut.set_result("DEAD")
                state.waiters.clear()
            return
        self._subscribed_channels.add("actor:" + spec.actor_id.hex())
        await self._submit_actor_creation(spec, deps)

    async def _submit_actor_creation(self, spec: TaskSpec, deps: List[ObjectID]):
        try:
            sched_class = spec.scheduling_class()
            pool = self._lease_pools.setdefault(sched_class, _LeasePool())
            grant = await self._acquire_lease(pool, spec)
            if grant.get("chip_ids"):
                # the actor owns its lease's chips for life; the worker
                # exports them before __init__ runs
                spec.chip_ids = grant["chip_ids"]
            client = await self._client_for(grant["worker_address"])
            reply = await client.call("push_task", cloudpickle.dumps(spec), timeout=None)
            if reply.get("error") is not None:
                try:
                    (err, tb), _ = ser.deserialize(reply["error"])
                    cause = f"creation task failed: {type(err).__name__}: {err}"
                except Exception:
                    cause = "creation task failed"
                await self.gcs.call("actor_failed", {
                    "actor_id": spec.actor_id, "cause": cause,
                })
                state = self._actors.get(spec.actor_id)
                if state is not None:
                    state.death_cause = cause
        except BaseException as e:  # noqa: BLE001
            try:
                await self.gcs.call("actor_failed", {
                    "actor_id": spec.actor_id,
                    "cause": f"creation failed: {type(e).__name__}: {e}",
                })
            except Exception:
                pass
        finally:
            for oid in deps:
                self._unpin_task_dep(oid)

    def _on_actor_update(self, payload):
        info = payload["actor"]
        state = self._actors.get(info.actor_id)
        if state is None:
            state = self._actors[info.actor_id] = _ActorState(actor_id=info.actor_id)
        state.state = info.state
        state.address = info.address
        state.death_cause = info.death_cause
        if info.state in ("DEAD", "RESTARTING"):
            # tear down the fast lane: buffered calls flush through the
            # asyncio path, which owns death/restart semantics
            lane = self._actor_lanes.pop(info.actor_id, None)
            if lane is not None:
                lane.close()
        if info.state == "DEAD":
            self._drop_actor_sub(info.actor_id)
        if info.state in ("ALIVE", "DEAD"):
            state.restart_in_flight = False
            for fut in state.waiters:
                if not fut.done():
                    fut.set_result(info.state)
            state.waiters.clear()
        elif (info.state == "RESTARTING" and state.owned
              and state.creation_spec is not None and not state.restart_in_flight):
            # the owner drives restarts: resubmit the creation task on a fresh
            # lease (ref: gcs_actor_manager.cc:858 RestartActor — here the
            # owner, not the GCS, re-runs the creation path)
            state.restart_in_flight = True
            spec = state.creation_spec
            spec.task_id = TaskID.for_actor_task(info.actor_id)
            self.io.spawn(self._submit_actor_creation(spec, []))

    async def _ensure_actor_sub(self, actor_id: ActorID) -> None:
        """Per-actor keyed subscription (gcs.py _publish_actor).
        Concurrent callers share one in-flight subscribe task, so a
        failure is seen by ALL of them (a flag-only guard would let the
        second caller proceed unsubscribed and stall out its alive-wait
        when the first caller's RPC failed)."""
        channel = "actor:" + actor_id.hex()
        if channel in self._subscribed_channels:
            return
        task = self._actor_sub_tasks.get(channel)
        if task is None:
            async def _sub():
                await self.gcs.call("subscribe", {"channels": [channel]})
                self._subscribed_channels.add(channel)

            task = self._actor_sub_tasks[channel] = \
                asyncio.ensure_future(_sub())
            task.add_done_callback(
                lambda _: self._actor_sub_tasks.pop(channel, None))
        await asyncio.shield(task)

    def _drop_actor_sub(self, actor_id: ActorID) -> None:
        """DEAD is terminal: release the keyed subscription on both
        sides (the GCS pops its index when it PUBLISHES the death, but a
        borrower that subscribed after that publish re-created it)."""
        channel = "actor:" + actor_id.hex()
        if channel in self._subscribed_channels:
            self._subscribed_channels.discard(channel)
            self.io.spawn(self.gcs.call(
                "unsubscribe", {"channels": [channel]}))

    async def _wait_actor_alive(self, actor_id: ActorID, timeout: float = 120.0) -> _ActorState:
        # subscribe-then-read: the authoritative get_actor below runs
        # AFTER the subscription is live, so no transition is missed
        await self._ensure_actor_sub(actor_id)
        state = self._actors.get(actor_id)
        if state is None:
            info = await self.gcs.call("get_actor", {"actor_id": actor_id})
            state = self._actors[actor_id] = _ActorState(actor_id=actor_id)
            if info is not None:
                state.state, state.address = info.state, info.address
                state.death_cause = info.death_cause
        while state.state != "ALIVE":
            if state.state == "DEAD":
                # covers the borrow-after-death path, where no DEAD
                # update will ever arrive to trigger the drop
                self._drop_actor_sub(actor_id)
                raise exc.ActorDiedError(actor_id, state.death_cause)
            fut = asyncio.get_event_loop().create_future()
            state.waiters.append(fut)
            await asyncio.wait_for(fut, timeout)
        return state

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args: tuple,
                          kwargs: dict, opts: dict) -> List[ObjectRef]:
        packed, deps = self._pack_args(args, kwargs)
        num_returns = opts.get("num_returns", 1)
        spec = TaskSpec(
            task_id=TaskID.for_actor_task(actor_id),
            job_id=self.job_id,
            function=FunctionDescriptor(blob_id="", repr_name=method_name,
                                        method_name=method_name),
            args=packed,
            num_returns=num_returns,
            actor_id=actor_id,
            max_retries=opts.get("max_task_retries", 0),
            owner_address=self.address,
        )
        from ..util.tracing import inject_trace_ctx

        inject_trace_ctx(spec)
        return_ids = spec.return_ids()
        refs = [ObjectRef(oid, self.address) for oid in return_ids]
        # registered so borrower fetch_object sees in-flight returns as
        # pending rather than gone
        self._inflight.setdefault(spec.task_id,
                                  {"canceled": False, "worker_address": None})
        if self._actor_lane_submit(spec, deps, return_ids):
            return refs
        self._actor_lane_blocked.add(actor_id)
        self.io.spawn(self._submit_actor_task(spec, deps))
        return refs

    def _actor_lane_submit(self, spec: TaskSpec, deps: List[ObjectID],
                           return_ids: List[ObjectID]) -> bool:
        """Route the call through the actor's fast lane. Once a lane
        exists ALL calls from this owner must ride it (ring FIFO is the
        ordering guarantee). A lane may only OPEN on the first-ever call
        to the actor from this owner — if any call already took the
        asyncio path, opening a lane later could reorder around the
        in-flight stream, so the actor is lane-blocked for good."""
        if self._lane_pool is None:  # native plane disabled
            return False
        known = self._actors.get(spec.actor_id)
        if known is not None and known.state == "DEAD":
            # the asyncio path raises ActorDiedError with the cause;
            # the ring would just see a dead socket
            return False
        lane = self._actor_lanes.get(spec.actor_id)
        if lane is None:
            if deps or spec.actor_id in self._actor_lane_blocked:
                return False
            if len(self._actor_lanes) >= self.cfg.actor_lane_max:
                # each lane costs two shm rings + a flusher/reply thread
                # pair; at envelope actor counts (1k+) that is thousands
                # of threads — beyond the cap, calls stay on the asyncio
                # path (the lane is a hot-actor latency optimization,
                # not a correctness feature)
                return False
            from .fastlane import ActorLane

            # double-checked under the create lock: ActorLane() is
            # side-effecting (attach coroutine + shm rings keyed by
            # (actor, worker, pid)), so a lost setdefault race would
            # leave an orphan lane attached to the SAME rings as the
            # winner — its reply thread then steals replies it has no
            # pending entry for, and the caller's get() times out
            with self._actor_lane_create_lock:
                lane = self._actor_lanes.get(spec.actor_id)
                if lane is None:
                    lane = self._actor_lanes[spec.actor_id] = ActorLane(
                        self, spec.actor_id)
        event = threading.Event()
        for oid in return_ids:
            self._lane_events[oid] = event
        if lane.submit(spec, event):
            return True
        for oid in return_ids:
            self._lane_events.pop(oid, None)
        return False

    async def _submit_actor_task(self, spec: TaskSpec, deps: List[ObjectID]):
        try:
            state = await self._wait_actor_alive(spec.actor_id)
            spec.seq_no = state.seq_no
            state.seq_no += 1
            retries_left = spec.max_retries  # actor default: in-flight tasks
            while True:                      # fail on death (ref: max_task_retries)
                try:
                    client = await self._client_for(state.address)
                    reply = await client.call("push_task", cloudpickle.dumps(spec), timeout=None)
                    self._handle_task_reply(spec, reply)
                    return
                except ConnectionLost:
                    prev_address = state.address
                    state.state = "RESTARTING" if state.state == "ALIVE" else state.state
                    if retries_left <= 0:
                        self._store_error(spec, exc.ActorDiedError(
                            spec.actor_id,
                            "the actor died while this call was in flight "
                            "(set max_task_retries to retry on restart)"))
                        return
                    retries_left -= 1
                    try:
                        state = await self._wait_actor_alive(spec.actor_id)
                    except exc.ActorDiedError as e:
                        self._store_error(spec, e)
                        return
                    if state.address == prev_address:
                        self._store_error(spec, exc.ActorDiedError(spec.actor_id, "unreachable"))
                        return
        except BaseException as e:  # noqa: BLE001
            self._store_error(spec, e)
        finally:
            self._inflight.pop(spec.task_id, None)
            for oid in deps:
                self._unpin_task_dep(oid)

    # ---------------------------------------------------- placement groups
    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str, name: str = "") -> "PlacementGroupID":
        from .ids import PlacementGroupID

        pg_id = PlacementGroupID.of(self.job_id)
        self.io.run(self.gcs.call("create_placement_group", {
            "pg_id": pg_id, "bundles": bundles, "strategy": strategy,
            "name": name,
        }))
        return pg_id

    def remove_placement_group(self, pg_id) -> None:
        self.io.run(self.gcs.call("remove_placement_group", {"pg_id": pg_id}))

    def wait_placement_group(self, pg_id, timeout: Optional[float]) -> bool:
        reply = self.io.run(
            self.gcs.call("wait_placement_group_ready",
                          {"pg_id": pg_id, "timeout": timeout}),
            timeout=None if timeout is None else timeout + 30)
        return reply["status"] == "ready"

    def get_placement_group_info(self, pg_id=None, name: str = "") -> Optional[dict]:
        payload = {"pg_id": pg_id} if pg_id is not None else {"name": name}
        return self.io.run(self.gcs.call("get_placement_group", payload))

    def list_placement_groups(self) -> List[dict]:
        return self.io.run(self.gcs.call("list_placement_groups", {}))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        async def _kill():
            state = self._actors.get(actor_id)
            await self.gcs.call("kill_actor", {"actor_id": actor_id,
                                               "cause": "ray_tpu.kill"})
            if state is not None and state.address:
                try:
                    client = await self._client_for(state.address)
                    await client.call("kill_self", {}, timeout=2)
                except Exception:
                    pass
        if threading.current_thread() is self.io.thread:
            # kill() can be reached from a destructor GC runs on the io
            # loop thread itself (e.g. a dataset coordinator handle);
            # blocking there would deadlock the loop — fire and forget
            self.io.spawn(_kill())
        else:
            self.io.run(_kill())

    def get_named_actor(self, name: str, namespace: str = "") -> ActorID:
        info = self.io.run(self.gcs.call("get_actor", {"name": name, "namespace": namespace}))
        if info is None or info.state == "DEAD":
            raise ValueError(f"Failed to look up actor '{name}'")
        state = self._actors.setdefault(info.actor_id, _ActorState(actor_id=info.actor_id))
        state.state, state.address = info.state, info.address
        return info.actor_id
