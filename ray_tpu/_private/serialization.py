"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

TPU-native analog of the reference serializer (ref:
python/ray/_private/serialization.py — msgpack header + pickle5 with
out-of-band buffers, vendored cloudpickle). Design goals here:

 * large numpy / jax host buffers travel out-of-band so the object store can
   hold them in shared memory and readers can map them zero-copy;
 * jax.Array device buffers are converted to host numpy on serialize (device
   data never lives in the host object store — the device plane keeps tensors
   in HBM; see ray_tpu/parallel/);
 * wire format: [u32 meta_len][meta json][u64 pickled_len][pickled]
   [u32 nbuffers][u64 len, bytes]* — a flat layout that can be written into a
   single shm segment and lazily sliced on read.
"""

from __future__ import annotations

import io
import json
import pickle
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

_HEADER = struct.Struct("<I")
_U64 = struct.Struct("<Q")

# Error metadata tags (analog of ray error types carried in object metadata).
META_PLAIN = "plain"
META_ERROR = "error"
META_ACTOR_HANDLE = "actor_handle"

_custom_serializers: Dict[type, Tuple[Callable, Callable]] = {}


def register_serializer(cls: type, *, serializer: Callable, deserializer: Callable) -> None:
    """Public custom-serializer hook (ref: ray.util.serialization)."""
    _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type) -> None:
    _custom_serializers.pop(cls, None)


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, buffers: List[pickle.PickleBuffer]):
        super().__init__(file, protocol=5, buffer_callback=buffers.append)

    def reducer_override(self, obj):
        for cls, (ser, de) in _custom_serializers.items():
            if isinstance(obj, cls):
                return (_reconstruct_custom, (cls.__module__, cls.__qualname__, ser(obj)))
        return super().reducer_override(obj)


def _reconstruct_custom(mod: str, qualname: str, payload):
    import importlib

    cls = importlib.import_module(mod)
    for part in qualname.split("."):
        cls = getattr(cls, part)
    _, de = _custom_serializers[cls]
    return de(payload)


def _device_to_host(obj: Any) -> Any:
    """Convert jax.Array leaves to numpy before pickling (pytree-aware).

    Looks jax up in sys.modules instead of importing it: if this process
    never imported jax there CANNOT be a jax.Array to convert, and a cold
    jax import costs ~2s — which used to tax every pool worker's first
    task result."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return obj
    if isinstance(obj, jax.Array):
        import numpy as np

        return np.asarray(obj)
    return obj


class SerializedParts:
    """A value pickled with out-of-band buffers, not yet written
    anywhere. ``total`` is the exact wire size, so a caller can allocate
    the destination (a shm mapping above all) and have ``write_into``
    lay the object down in ONE pass — for multi-GiB numpy/jax host
    buffers the flat-bytes path costs three extra full-size copies
    (bytearray zero-fill + assemble + bytes()), which is the difference
    between seconds and minutes at 10 GiB on a bandwidth-poor host."""

    __slots__ = ("meta", "pickled", "buffers", "raw", "total")

    def __init__(self, meta, pickled, buffers, raw, total):
        self.meta = meta
        self.pickled = pickled
        self.buffers = buffers
        self.raw = raw
        self.total = total

    def write_into(self, out) -> None:
        """Pack the full wire format into `out` (len == total) and
        release the pickle buffers."""
        off = 0
        _HEADER.pack_into(out, off, len(self.meta)); off += _HEADER.size
        out[off : off + len(self.meta)] = self.meta; off += len(self.meta)
        _U64.pack_into(out, off, len(self.pickled)); off += _U64.size
        out[off : off + len(self.pickled)] = self.pickled
        off += len(self.pickled)
        _HEADER.pack_into(out, off, len(self.raw)); off += _HEADER.size
        for rb in self.raw:
            _U64.pack_into(out, off, rb.nbytes); off += _U64.size
            out[off : off + rb.nbytes] = rb; off += rb.nbytes
        for b in self.buffers:
            b.release()
        self.buffers = self.raw = ()

    def to_bytes(self) -> bytes:
        out = bytearray(self.total)
        self.write_into(out)
        return bytes(out)


def serialize_parts(value: Any, metadata: str = META_PLAIN) -> SerializedParts:
    value = _device_to_host(value)
    buffers: List[pickle.PickleBuffer] = []
    f = io.BytesIO()
    _Pickler(f, buffers).dump(value)
    pickled = f.getvalue()
    meta = json.dumps({"m": metadata}).encode()
    raw_bufs = [b.raw() for b in buffers]
    total = (
        _HEADER.size + len(meta) + _U64.size + len(pickled) + _HEADER.size
        + sum(_U64.size + rb.nbytes for rb in raw_bufs)
    )
    return SerializedParts(meta, pickled, buffers, raw_bufs, total)


def serialize(value: Any, metadata: str = META_PLAIN) -> bytes:
    """Serialize `value` to the flat wire format."""
    return serialize_parts(value, metadata).to_bytes()


def serialize_into(value: Any, metadata: str = META_PLAIN) -> Tuple[bytes, int]:
    data = serialize(value, metadata)
    return data, len(data)


def get_metadata(data) -> str:
    (meta_len,) = _HEADER.unpack_from(data, 0)
    meta = bytes(data[_HEADER.size : _HEADER.size + meta_len])
    return json.loads(meta)["m"]


def deserialize(data) -> Tuple[Any, str]:
    """Deserialize from bytes/memoryview. Out-of-band buffers are zero-copy
    views into `data` when it is a memoryview over shm."""
    view = memoryview(data)
    off = 0
    (meta_len,) = _HEADER.unpack_from(view, off); off += _HEADER.size
    metadata = json.loads(bytes(view[off : off + meta_len]))["m"]; off += meta_len
    (pickled_len,) = _U64.unpack_from(view, off); off += _U64.size
    pickled = view[off : off + pickled_len]; off += pickled_len
    (nbufs,) = _HEADER.unpack_from(view, off); off += _HEADER.size
    buffers = []
    for _ in range(nbufs):
        (blen,) = _U64.unpack_from(view, off); off += _U64.size
        buffers.append(view[off : off + blen]); off += blen
    value = pickle.loads(pickled, buffers=buffers)
    return value, metadata


def serialize_error(err: BaseException) -> bytes:
    """Serialize an exception, falling back to a descriptive wrapper when the
    exception itself is unpicklable."""
    import traceback

    tb = "".join(traceback.format_exception(type(err), err, err.__traceback__))
    try:
        return serialize((err, tb), metadata=META_ERROR)
    except Exception:
        return serialize((RuntimeError(f"{type(err).__name__}: {err}"), tb), metadata=META_ERROR)
