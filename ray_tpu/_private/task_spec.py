"""Task specifications, resource sets, and scheduling classes.

TPU-native analog of the reference task model (ref: src/ray/common/task/
task_spec.h, src/ray/common/scheduling/ — ResourceSet, SchedulingClass).
Resources are float-valued named quantities; "TPU" is first-class next to
"CPU", and slice topology resources (e.g. "TPU-v5p-16-head") gang-schedule
whole ICI slices (ref: python/ray/_private/accelerators/tpu.py:401-403, here
promoted into the scheduler proper — see ray_tpu/parallel/topology.py).
"""

from __future__ import annotations

import enum
import hashlib
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID


class ArgKind(enum.IntEnum):
    VALUE = 0       # inline serialized bytes
    OBJECT_REF = 1  # ObjectID to resolve before execution


@dataclass
class TaskArg:
    kind: ArgKind
    value: Any = None          # serialized bytes for VALUE
    object_id: Optional[ObjectID] = None
    # owner address for OBJECT_REF args: small objects never touch
    # plasma — the executing worker fetches them from the owner (ref:
    # core_worker.proto GetObject / ownership model reference_count.h:66)
    owner: str = ""


class ResourceSet:
    """Float-valued named resources with TPU-aware comparison ops."""

    __slots__ = ("res",)

    def __init__(self, res: Optional[Dict[str, float]] = None):
        self.res = {k: float(v) for k, v in (res or {}).items() if v != 0}

    def fits(self, available: "ResourceSet") -> bool:
        return all(available.res.get(k, 0.0) + 1e-9 >= v for k, v in self.res.items())

    def subtract(self, other: "ResourceSet") -> None:
        for k, v in other.res.items():
            self.res[k] = self.res.get(k, 0.0) - v

    def add(self, other: "ResourceSet") -> None:
        for k, v in other.res.items():
            self.res[k] = self.res.get(k, 0.0) + v

    def copy(self) -> "ResourceSet":
        return ResourceSet(dict(self.res))

    def get(self, key: str, default: float = 0.0) -> float:
        return self.res.get(key, default)

    def is_empty(self) -> bool:
        return not self.res

    def to_dict(self) -> Dict[str, float]:
        return dict(self.res)

    def key(self) -> Tuple[Tuple[str, float], ...]:
        return tuple(sorted(self.res.items()))

    def __repr__(self):
        return f"ResourceSet({self.res})"

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self.res == other.res


# --- scheduling strategies (ref: python/ray/util/scheduling_strategies.py) ---

@dataclass
class DefaultSchedulingStrategy:
    """Hybrid policy: pack locally until threshold, then spread (ref:
    raylet/scheduling/policy/hybrid_scheduling_policy.h:50)."""


@dataclass
class SpreadSchedulingStrategy:
    pass


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str = ""
    soft: bool = False
    spill_on_unavailable: bool = False


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group_id: Optional[PlacementGroupID] = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class SliceSchedulingStrategy:
    """TPU-native: schedule onto a specific ICI slice / mesh sub-cube."""

    slice_name: str = ""
    host_index: int = -1


# Label match expressions (ref: python/ray/util/scheduling_strategies.py
# In:94 / NotIn / Exists / DoesNotExist + the node-label policy in
# raylet/scheduling/policy/node_label_scheduling_policy.h)
@dataclass
class In:
    values: List[str] = field(default_factory=list)

    def __init__(self, *args, values=None):
        # accept In("a", "b"), In(["a", "b"]) and In(values=[...])
        if values is None:
            values = args[0] if (len(args) == 1 and isinstance(
                args[0], (list, tuple))) else args
        self.values = list(values)


@dataclass
class NotIn:
    values: List[str] = field(default_factory=list)

    def __init__(self, *args, values=None):
        if values is None:
            values = args[0] if (len(args) == 1 and isinstance(
                args[0], (list, tuple))) else args
        self.values = list(values)


@dataclass
class Exists:
    pass


@dataclass
class DoesNotExist:
    pass


def label_expr_matches(labels: Dict[str, str], exprs: Dict[str, Any]) -> bool:
    """Does a node's label set satisfy every (key -> expression)?"""
    for key, expr in (exprs or {}).items():
        present = key in labels
        value = labels.get(key)
        if isinstance(expr, In):
            if not present or value not in expr.values:
                return False
        elif isinstance(expr, NotIn):
            if present and value in expr.values:
                return False
        elif isinstance(expr, Exists):
            if not present:
                return False
        elif isinstance(expr, DoesNotExist):
            if present:
                return False
        else:
            raise TypeError(f"unknown label expression {expr!r}")
    return True


@dataclass
class NodeLabelSchedulingStrategy:
    """Match nodes by label expressions: ``hard`` must hold, ``soft``
    breaks ties among hard-feasible nodes (ref: scheduling_strategies.py
    NodeLabelSchedulingStrategy:135)."""

    hard: Dict[str, Any] = field(default_factory=dict)
    soft: Dict[str, Any] = field(default_factory=dict)


SchedulingStrategy = Any  # union of the above


_scheduling_class_cache: Dict[Tuple, int] = {}
_scheduling_class_lock = threading.Lock()
_next_scheduling_class = [0]


def scheduling_class_of(resources: ResourceSet, strategy_key: str) -> int:
    """Intern (resources, strategy) into a dense int id (ref:
    SchedulingClass in task_spec.h; SchedulingKey normal_task_submitter.h:58)."""
    key = (resources.key(), strategy_key)
    with _scheduling_class_lock:
        sc = _scheduling_class_cache.get(key)
        if sc is None:
            sc = _next_scheduling_class[0]
            _next_scheduling_class[0] += 1
            _scheduling_class_cache[key] = sc
        return sc


@dataclass
class FunctionDescriptor:
    """Identifies executable code: a blob in the GCS function table."""

    blob_id: str            # sha1 of pickled function/class
    repr_name: str          # human-readable, for errors/observability
    method_name: str = ""   # for actor method calls

    @staticmethod
    def blob_id_for(pickled: bytes) -> str:
        return hashlib.sha1(pickled).hexdigest()


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function: FunctionDescriptor
    args: List[TaskArg] = field(default_factory=list)
    num_returns: int = 1
    resources: ResourceSet = field(default_factory=ResourceSet)
    scheduling_strategy: SchedulingStrategy = field(default_factory=DefaultSchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    # streaming generators (ref: _raylet.pyx:1138-1225 streaming_generator_*):
    # the executor reports each yielded object eagerly instead of one reply
    streaming: bool = False
    backpressure_items: int = 0   # 0 = unbounded producer
    # actor-related
    actor_id: Optional[ActorID] = None          # set for actor tasks
    actor_creation: bool = False                # creation task
    actor_max_restarts: int = 0
    actor_max_concurrency: int = 1
    actor_name: str = ""                        # named actors
    seq_no: int = 0                             # per-caller actor task ordering
    owner_address: str = ""                     # socket of the owning core worker
    runtime_env: Optional[dict] = None
    # physical TPU chips granted to the executing lease — the worker
    # exports them as TPU_VISIBLE_CHIPS before running user code (ref:
    # accelerators/tpu.py:31 promoted to per-lease scheduler state)
    chip_ids: Optional[List[int]] = None
    # span context (trace_id, parent_span_id) when tracing is enabled
    # (ref: tracing_helper.py — span context rides the task options)
    trace_ctx: Optional[tuple] = None
    # tail tolerance (The Tail at Scale): a task declared idempotent may
    # be speculatively re-executed — both executions can run (and seal)
    # concurrently, so the body must be deterministic and side-effect
    # free beyond its return objects. speculation: "" = default (hedge
    # iff idempotent and task_speculation_enabled), "auto" = same,
    # "off" = never hedge this task even when idempotent.
    idempotent: bool = False
    speculation: str = ""

    def is_actor_task(self) -> bool:
        return self.actor_id is not None and not self.actor_creation

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_return(self.task_id, i + 1) for i in range(self.num_returns)]

    def scheduling_class(self) -> int:
        strat = self.scheduling_strategy
        return scheduling_class_of(self.resources, type(strat).__name__ + repr(strat))

    @classmethod
    def lane_probe(cls, job_id: JobID, owner_address: str) -> "TaskSpec":
        """A {CPU:1} default-strategy spec used to lease a worker for a
        fast lane (ray_tpu/_private/fastlane.py) — the lane then streams
        many real tasks through the one lease, the way the reference
        reuses a leased worker per SchedulingKey."""
        return cls(
            task_id=TaskID.for_normal_task(job_id),
            job_id=job_id,
            function=FunctionDescriptor(blob_id="", repr_name="__lane__"),
            resources=ResourceSet({"CPU": 1}),
            owner_address=owner_address,
        )
