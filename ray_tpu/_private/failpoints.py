"""Named failpoints: runtime fault injection at the hazard boundaries.

The error-plane lint (devtools/graftlint swallow/cleanup/rpc-timeout
passes) proves statically that faults *can* surface; this harness
proves dynamically that they *do*. Each control-plane hazard boundary
declares a named site::

    from . import failpoints
    if failpoints.fire("rpc.client.send", detail=method) == "drop":
        ...skip the write, let the timeout/retry machinery engage...

Unarmed, ``fire`` is one dict lookup on an empty dict — nothing to
configure out in production. Armed (``RAY_TPU_FAILPOINTS`` env var, the
``failpoints`` config flag — which the driver's ``_system_config``
propagates to every worker — or programmatic :func:`arm`), a site
performs its configured action when hit:

  * ``raise``  — raise :class:`FailpointError` naming the site, so the
    chaos harness can assert the surfaced error is *attributed*;
  * ``delay``  — sleep ``arg`` seconds (default 0.05) then proceed,
    modelling stragglers and slow networks;
  * ``drop``   — return ``"drop"``; the call site skips the operation
    (an unsent frame, an unanswered request), modelling loss.
  * ``slow``   — sleep ``arg`` seconds (default 0.25) then proceed:
    site-scoped injected *latency* rather than a fault. Distinct from
    ``delay`` so tail-tolerance benches/tests can arm a deterministic
    straggler (e.g. ``worker.task.run@<node_hex>=slow:2``) without
    tripping chaos legs that treat delay/raise/drop hits as injected
    faults that must surface as errors.

Spec grammar (comma-separated)::

    site=action[:arg][:max_hits]
    rpc.server.dispatch=delay:0.05:5,raylet.lease.grant=raise
    rpc.client.send@request_worker_lease=drop:0:2

``site@detail`` keys scope the fault to one RPC method / one detail
value; they match before the bare site key. ``max_hits`` bounds how
many times the action fires (0 or absent = unlimited) — essential for
drop-faults on non-retried paths, where an unbounded drop would turn
injected loss into a permanent hang instead of a recoverable blip.

Mirrors the reference fault-injection plane (ref: rpc_chaos.h
RpcFailure + testing_rpc_failure flag) but is callable from *any*
subsystem boundary, not just RPC interposition.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

__all__ = [
    "FailpointError", "SITES", "fire", "afire", "arm", "disarm",
    "hit_counts", "active_spec",
]


class FailpointError(RuntimeError):
    """An injected fault. The message names the armed site so chaos
    assertions can attribute the surfaced error to the injection."""


# canonical site registry: chaos_smoke draws from this, and the
# failpoint tests assert instrumented modules only use declared names
SITES = (
    "rpc.client.send",       # RpcClient.call, before the request frame write
    "rpc.server.dispatch",   # RpcServer._dispatch, before the handler runs
    "raylet.lease.grant",    # Raylet.handle_request_worker_lease entry
    "raylet.heartbeat",      # raylet clock-sync ping round against the GCS
    "object.seal",           # SharedObjectStore.seal entry
    "spill.write",           # SharedObjectStore staged-spill flush to disk
    "worker.task.run",       # TaskExecutor.execute_normal, detail=node hex
    "serve.replica.handle",  # serve Replica.handle, detail=deployment name
    "serve.kv_handoff",      # prefill->decode KV ship, detail=deployment
)

_lock = threading.Lock()
_override_spec: Optional[str] = None       # arm() beats config/env
_parsed_for: Optional[str] = None          # spec string the rules came from
_rules: Dict[str, dict] = {}
_hits: Dict[str, int] = {}


def _current_spec() -> str:
    if _override_spec is not None:
        return _override_spec
    try:
        from .config import global_config
        return global_config().failpoints
    except Exception:
        return ""


def _parse(spec: str) -> Dict[str, dict]:
    rules: Dict[str, dict] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        key, _, rhs = entry.partition("=")
        parts = rhs.split(":")
        action = parts[0].strip()
        if action not in ("raise", "delay", "drop", "slow"):
            continue
        arg = float(parts[1]) if len(parts) > 1 and parts[1] else 0.0
        max_hits = int(float(parts[2])) if len(parts) > 2 and parts[2] else 0
        rules[key.strip()] = {
            "key": key.strip(), "action": action, "arg": arg,
            "max_hits": max_hits, "fired": 0,
        }
    return rules


def arm(spec: str) -> None:
    """Programmatically arm this process (tests); overrides config/env
    until :func:`disarm`. Resets hit counters."""
    global _override_spec
    with _lock:
        _override_spec = spec
        _refresh_locked(force=True)


def disarm() -> None:
    """Drop the programmatic override, falling back to the config flag
    (usually empty → all sites inert)."""
    global _override_spec
    with _lock:
        _override_spec = None
        _refresh_locked(force=True)


def _refresh_locked(force: bool = False) -> None:
    global _parsed_for, _rules
    spec = _current_spec()
    if force or spec != _parsed_for:
        _parsed_for = spec
        _rules = _parse(spec)
        _hits.clear()


def _begin(name: str, detail: Optional[str]) -> Optional[dict]:
    """Match + hit accounting under the lock; returns the rule to apply
    (action performed by the sync/async wrappers, outside the lock)."""
    with _lock:
        _refresh_locked()
        if not _rules:
            return None
        rule = None
        if detail is not None:
            rule = _rules.get(f"{name}@{detail}")
        if rule is None:
            rule = _rules.get(name)
        if rule is None:
            return None
        if rule["max_hits"] and rule["fired"] >= rule["max_hits"]:
            return None
        rule["fired"] += 1
        _hits[rule["key"]] = rule["fired"]
        return dict(rule)


def fire(name: str, detail: Optional[str] = None) -> Optional[str]:
    """Sync failpoint. Returns None (inert/pass), "delay" (after
    sleeping), or "drop" (caller skips the op); raises FailpointError
    for raise-armed sites."""
    rule = _begin(name, detail)
    if rule is None:
        return None
    if rule["action"] == "raise":
        raise FailpointError(
            f"failpoint '{rule['key']}' injected fault at {name}"
            + (f" (detail={detail})" if detail else ""))
    if rule["action"] == "delay":
        time.sleep(rule["arg"] or 0.05)
        return "delay"
    if rule["action"] == "slow":
        time.sleep(rule["arg"] or 0.25)
        return "slow"
    return "drop"


async def afire(name: str, detail: Optional[str] = None) -> Optional[str]:
    """Async failpoint: as :func:`fire` but delays via asyncio.sleep so
    an injected straggler never blocks the io loop it runs on."""
    rule = _begin(name, detail)
    if rule is None:
        return None
    if rule["action"] == "raise":
        raise FailpointError(
            f"failpoint '{rule['key']}' injected fault at {name}"
            + (f" (detail={detail})" if detail else ""))
    if rule["action"] == "delay":
        import asyncio
        await asyncio.sleep(rule["arg"] or 0.05)
        return "delay"
    if rule["action"] == "slow":
        import asyncio
        await asyncio.sleep(rule["arg"] or 0.25)
        return "slow"
    return "drop"


def hit_counts() -> Dict[str, int]:
    """Spec-key -> times fired, for chaos assertions ("the armed site
    actually tripped")."""
    with _lock:
        return dict(_hits)


def active_spec() -> str:
    with _lock:
        _refresh_locked()
        return _parsed_for or ""
