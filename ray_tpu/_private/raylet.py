"""Raylet: per-node manager — lease scheduling, worker pool, object directory.

TPU-native analog of the reference raylet (ref: src/ray/raylet/node_manager.h,
HandleRequestWorkerLease node_manager.cc:2003; scheduling/
cluster_task_manager.h; worker_pool.h; wait_manager.h; local_object_manager.h).

Design deltas from the reference, driven by the TPU runtime model:
 * the object store is a shared tmpfs namespace per session (object_store.py),
   so the dependency manager's pull path degenerates to a directory lookup on
   one host — multi-host transfer rides the DCN object-transfer service
   (future native component) behind the same `wait_objects` contract;
 * scheduling understands TPU chips natively: every lease carrying "TPU"
   resources is assigned physical chip ids from a per-chip accounting pool
   (whole chips exclusive, fractional leases bin-packed onto shared chips —
   `_allocate_chips`), and the executing worker exports them as
   TPU_VISIBLE_CHIPS / RAY_TPU_CHIP_IDS before user code runs (ref:
   python/ray/_private/accelerators/tpu.py:31, promoted from env-var
   convention into scheduler state; tests/test_topology.py). Slice-spread
   placement-group gangs map onto one ICI slice in host_index order
   (gcs._plan_bundles_on_slice; SURVEY §5.8, §7.1.2);
 * hybrid scheduling policy: pack onto the local node below a utilization
   threshold, spread above it, spill to the best remote node otherwise
   (ref: policy/hybrid_scheduling_policy.h:50).
"""

from __future__ import annotations

import asyncio
import collections
import json
import os
import random
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import failpoints
from .config import global_config, session_log_dir
from .ids import ActorID, NodeID, ObjectID, WorkerID
from .object_store import SharedObjectStore
from .rpc import (ConnectionLost, RpcClient, RpcError, RpcServer,
                  ServerConnection, background)
from .task_spec import (
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    ResourceSet,
    SpreadSchedulingStrategy,
    label_expr_matches,
)


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    pid: int
    address: str                      # the worker's own RPC socket
    conn: Optional[ServerConnection] = None
    idle_since: float = field(default_factory=time.monotonic)
    lease: Optional["Lease"] = None
    actor_id: Optional[ActorID] = None  # dedicated actor worker
    alive: bool = True


@dataclass
class Lease:
    lease_id: int
    worker: WorkerHandle
    resources: ResourceSet
    owner_address: str
    pg_key: Optional[tuple] = None    # (pg_id, bundle_idx) the lease lives in
    # fast-lane leases are preemptible when idle: under pending demand
    # the raylet pushes "reclaim_lease" to the owner, who returns the
    # worker if the lane has nothing in flight
    lane: bool = False
    conn: Optional[ServerConnection] = None
    reclaim_requested_at: float = 0.0
    # TPU chips granted to this lease as [(chip_id, fraction)] — the
    # worker sees them as TPU_VISIBLE_CHIPS (ref:
    # python/ray/_private/accelerators/tpu.py:31, promoted from env-var
    # convention to first-class per-lease accounting)
    chips: List[tuple] = field(default_factory=list)
    # CPU share temporarily given back while the worker blocks on object
    # resolution (ref: NotifyDirectCallTaskBlocked in node_manager.cc —
    # without this, a gang of dep-waiting workers deadlocks the node)
    blocked_cpu: Optional[ResourceSet] = None


@dataclass
class _PendingLease:
    payload: dict
    future: asyncio.Future
    resources: ResourceSet
    queued_at: float = 0.0  # monotonic; damps queue->spillback bouncing


class NodeResources:
    """Per-node resource accounting. Backed by the native lease-scheduler
    engine when available (native/core_tables.cc — the C++ half of the
    reference's cluster_resource_scheduler/local_resource_manager pair);
    the Python ResourceSet arithmetic is the fallback."""

    _NODE = 1  # single-node handle inside the native engine

    def __init__(self, total: Dict[str, float]):
        self.total = ResourceSet(total)
        self._native = None
        try:
            from .._native import LeaseScheduler, native_unavailable_reason

            if native_unavailable_reason() is None:
                self._native = LeaseScheduler(local_node=self._NODE)
                self._native.node_upsert(self._NODE, self.total.to_dict(),
                                         self.total.to_dict())
        except Exception:
            self._native = None
        self._available = self.total.copy()  # fallback bookkeeping

    @property
    def available(self) -> ResourceSet:
        if self._native is not None:
            return ResourceSet({
                k: self._native.avail(self._NODE, k)
                for k in self.total.to_dict()})
        return self._available

    def try_allocate(self, req: ResourceSet) -> bool:
        if self._native is not None:
            return self._native.try_allocate(self._NODE, req.to_dict())
        if not req.fits(self._available):
            return False
        self._available.subtract(req)
        return True

    def force_allocate(self, req: ResourceSet) -> None:
        """Unconditional subtraction — availability may go transiently
        negative (a dep-blocked worker resuming re-takes its CPU even if
        the node is momentarily oversubscribed, matching the reference's
        unblock semantics)."""
        if self._native is not None:
            self._native.release(self._NODE,
                                 {k: -v for k, v in req.to_dict().items()})
            return
        self._available.subtract(req)

    def release(self, req: ResourceSet) -> None:
        if self._native is not None:
            self._native.release(self._NODE, req.to_dict())
            return
        self._available.add(req)
        # clamp against float drift
        for k, v in self._available.res.items():
            cap = self.total.get(k)
            if v > cap:
                self._available.res[k] = cap

    def utilization(self) -> float:
        avail = self.available
        best = 0.0
        for k, cap in self.total.res.items():
            if cap > 0:
                best = max(best, 1.0 - avail.get(k, 0.0) / cap)
        return best


class Raylet:
    def __init__(
        self,
        node_id: NodeID,
        session_name: str,
        socket_path: str,
        gcs_address: str,
        resources: Dict[str, float],
        store: SharedObjectStore,
        labels: Optional[Dict[str, str]] = None,
        advertise_host: Optional[str] = None,
    ):
        self.node_id = node_id
        self.session_name = session_name
        self.socket_path = socket_path
        self.gcs_address = gcs_address
        self.labels = labels or {}
        self.store = store
        self.resources = NodeResources(resources)
        self.server = RpcServer(socket_path, name=f"raylet-{node_id.hex()[:8]}",
                                advertise_host=advertise_host)
        self.server.register_all(self)
        self.server.on_disconnect = self._on_disconnect
        # constructed in start() from the (possibly port-resolved) gcs_address
        self.gcs: RpcClient = None  # type: ignore[assignment]
        self.transfer = None
        self.syncer = None

        cfg = global_config()
        self.cfg = cfg
        # bulk transfer plane: listener constructed in start() (needs the
        # resolved server address); the PullManager lives from birth so a
        # wait_objects arriving in the start() window can't hit None
        from .object_transfer import PullManager

        self.pulls = PullManager(
            cfg.object_transfer_max_inflight_bytes, self._pull)
        # worker pool
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: List[WorkerHandle] = []
        self._starting: int = 0
        self._register_waiters: List[asyncio.Future] = []
        max_workers = cfg.num_workers_soft_limit
        if max_workers > 0:
            self.max_workers = max_workers
        else:
            # The pool must be able to back every leasable CPU slot: the
            # node's ADVERTISED CPU resource, not the host core count —
            # a node faking num_cpus=8 on a 1-core box (tests, oversub-
            # scribed orchestration) would otherwise wedge the 5th
            # lease forever behind a 4-worker cap (actors hold workers
            # for life). (ref: worker_pool.h prestart/soft-limit ties
            # to num_cpus the same way.)
            ncpu = int(self.resources.total.get("CPU", 0))
            self.max_workers = max(4, ncpu, os.cpu_count() or 1)
        # leases
        self._leases: Dict[int, Lease] = {}
        self._next_lease_id = 1
        self._pending_leases: List[_PendingLease] = []
        self._worker_seq = 0  # names this node's worker log files
        # lease-request dedup by client request id, so a retried request
        # (reply lost, injected chaos, flaky DCN) returns the SAME grant
        # instead of leaking a second worker (ref: retryable_grpc_client.h +
        # lease idempotency in node_manager)
        self._lease_rid_grants: Dict[str, dict] = {}
        self._lease_rid_pending: Dict[str, asyncio.Future] = {}
        self._lease_id_to_rid: Dict[int, str] = {}
        # object directory + wait manager
        self._sealed: Dict[ObjectID, int] = {}          # oid -> size
        self._object_waiters: Dict[ObjectID, List[asyncio.Future]] = {}
        self._lost_objects: Set[ObjectID] = set()
        # inter-node object transfer (ref: object_manager/pull_manager.h:57,
        # push_manager.h:32 — chunked transfer over the control transport)
        self._peer_clients: Dict[str, RpcClient] = {}
        # broadcast-tree sender slots: oid -> {puller_hex: grant expiry}
        self._transfer_tokens: Dict[ObjectID, Dict[str, float]] = {}
        self._transfer_token_high: Dict[ObjectID, int] = {}  # high-water
        # grants per control connection, released the moment the puller's
        # connection drops (a crashed puller must not pin a sender slot
        # for the wall-clock TTL) — the TTL stays as the backstop
        self._token_conn_grants: Dict[object, set] = {}
        self._token_conn_watchers: Dict[object, asyncio.Task] = {}
        self._pull_sources: Dict[ObjectID, NodeID] = {}   # observability
        # cluster view (for spillback) — node_id -> (address, available)
        self._remote_nodes: Dict[NodeID, Tuple[str, ResourceSet]] = {}
        # hub-declared-dead nodes (node channel "removed"): the gossip
        # syncer cross-checks applied entries against this so a laggard
        # peer can't resurrect a dead node after its tombstone TTL
        # lapses; bounded so unbounded churn can't grow it forever
        self._dead_node_hexes: "collections.OrderedDict[str, None]" = (
            collections.OrderedDict())
        # node_id -> labels (incl. this node), for label-match scheduling
        self._node_labels: Dict[NodeID, Dict[str, str]] = {}
        self._worker_conns: Dict[ServerConnection, WorkerID] = {}
        self._spill_rr = 0
        self._resource_seq = 0
        self._subprocs: List[subprocess.Popen] = []
        # forkserver worker factory (see _spawn_via_factory)
        self._factory_proc: Optional[subprocess.Popen] = None
        self._factory_reader = None
        self._factory_writer = None
        self._factory_lock = asyncio.Lock()
        self._factory_pids: List[int] = []
        # (pg_id, bundle_idx) -> bundle-local resource accounting: reserved
        # total + what's still leasable within it (ref:
        # placement_group_resource_manager.h bundle resource bookkeeping)
        self._pg_bundles: Dict[tuple, NodeResources] = {}
        # per-chip TPU accounting: chip i carries a used fraction in
        # [0, 1]; whole-chip leases take exclusive chips, fractional
        # leases bin-pack onto shared ones (ref: accelerators/tpu.py
        # TPU_VISIBLE_CHIPS isolation + GPU fractional semantics)
        self._chip_used: List[float] = \
            [0.0] * int(self.resources.total.get("TPU", 0))
        # smoothed NTP-style estimate of (GCS clock - local clock);
        # None until the first clock-sync round completes
        self._clock_offset: Optional[float] = None
        # stall sentinel: per-scheduling-class EMA of completed task
        # durations (the adaptive RUNNING-too-long threshold's memory),
        # plus currently-flagged stalls so each hang alerts once
        self._class_ema: Dict[str, float] = {}
        self._stalled_tasks: Dict[str, dict] = {}
        self._stalled_transfers: Dict[str, dict] = {}
        # tail tolerance: node hex -> straggler score (EMA lateness over
        # cluster mean, from GCS straggler_scores), refreshed each
        # watchdog tick; scheduling deprioritizes nodes past threshold
        self._straggler_scores: Dict[str, float] = {}
        self._drained_workers: Set[int] = set()  # pids killed for draining
        # black-box plane: this raylet's own flight ring, plus the pids
        # whose exit we ORDERED (graceful shutdown pushes) — their
        # disconnect discards the flight file instead of bundling it
        self._blackbox = None
        self._expected_exits: Set[int] = set()
        from .config import TEMP_ROOT

        self._session_dir = os.path.join(TEMP_ROOT, session_name)

    # ------------------------------------------------------------------ setup
    async def start(self):
        await self.server.start()
        self.socket_path = self.server.address  # resolved (TCP port 0)
        # bulk transfer plane: its own listener so gigabyte chunk streams
        # never head-of-line-block control RPCs (object_transfer.py)
        from .object_transfer import TransferServer, _parse_addr

        kind = _parse_addr(self.server.address)
        if kind[0] == "unix":
            self.transfer = TransferServer(
                self.store, self.server.address + ".xfer",
                on_puller_gone=self._on_transfer_puller_gone)
        else:
            # bind-all, advertise the node's routable IP — same split the
            # control server uses (NAT/container hosts can't bind the
            # address they advertise)
            self.transfer = TransferServer(
                self.store, "0.0.0.0:0", advertise_host=kind[1],
                on_puller_gone=self._on_transfer_puller_gone)
        await self.transfer.start()
        self.gcs = RpcClient(self.gcs_address)
        await self.gcs.connect()
        self.gcs.on_push("pubsub:resources", self._on_remote_resources)
        self.gcs.on_push("pubsub:node", self._on_node_event)
        self.gcs.on_push("pubsub:object", self._on_object_event)
        reply = await self.gcs.call("register_node", {
            "node_id": self.node_id,
            "address": self.server.address,
            "resources_total": self.resources.total.to_dict(),
            "resources_available": self.resources.available.to_dict(),
            "labels": self.labels,
            "slice_name": self.labels.get("slice_name", ""),
            "host_index": int(self.labels.get("host_index", 0)),
            "store_dir": self.store.dir,
            "transfer_address": self.transfer.address,
        })
        self._node_labels[self.node_id] = dict(self.labels)
        for info in reply["nodes"]:
            if info.node_id != self.node_id and info.alive:
                self._remote_nodes[info.node_id] = (info.address, ResourceSet(info.resources_available))
                self._node_labels[info.node_id] = dict(info.labels or {})
        if self.cfg.resource_sync_mode == "gossip":
            # peer availability rides anti-entropy rounds, not a hub
            # fan-out: the GCS stays out of the O(N^2) broadcast path
            # (node/object events remain hub channels — membership and
            # the object directory are authoritative state, not gossip)
            from .syncer import ResourceSyncer

            self.syncer = ResourceSyncer(
                self, interval_s=self.cfg.resource_sync_interval_s,
                fanout=self.cfg.resource_sync_fanout)
            self.syncer.local_update(
                self.resources.available.to_dict(), [],
                self._resource_seq)
            self.syncer.start()
            await self.gcs.call(
                "subscribe", {"channels": ["node", "object"]})
        else:
            await self.gcs.call(
                "subscribe", {"channels": ["resources", "node", "object"]})
        self.gcs.on_reconnect.append(self._on_gcs_reconnect)
        if self.cfg.prestart_workers:
            for _ in range(min(2, self.max_workers)):
                self._spawn_worker()
        if self.cfg.memory_monitor_refresh_ms > 0:
            background(self._memory_monitor_loop())
        if self.cfg.clock_sync_interval_s > 0:
            background(self._clock_sync_loop())
        if self.cfg.task_watchdog_interval_s > 0:
            background(self._task_watchdog_loop())
        if self.cfg.blackbox_enabled:
            from . import blackbox

            self._blackbox = blackbox.FlightRecorder(
                "raylet", self._session_dir,
                ident=self.server.address,
                node_id=self.node_id.hex(),
                ring_size=self.cfg.blackbox_ring_size,
                flush_interval_s=self.cfg.blackbox_flush_interval_s,
                inflight_provider=self._blackbox_inflight)
            self._blackbox.start()

    def _blackbox_inflight(self):
        """Flight-ring view of what this raylet is holding right now:
        granted leases (the tasks a postmortem must implicate) plus the
        worker pool. Kept cheap — it runs on every flight flush."""
        items = []
        for lease_id, lease in list(self._leases.items())[:200]:
            items.append({
                "kind": "lease",
                "lease_id": lease_id,
                "worker_pid": lease.worker.pid,
                "actor_id": lease.worker.actor_id.hex()
                if lease.worker.actor_id else None,
                "owner": lease.owner_address,
            })
        for w in list(self._workers.values())[:200]:
            items.append({
                "kind": "worker",
                "worker_id": w.worker_id.hex(),
                "pid": w.pid,
                "alive": w.alive,
            })
        return items

    async def _clock_sync_loop(self):
        """Estimate this node's clock offset against the GCS clock by
        piggybacking on the ping RPC (NTP-style: offset = remote_time -
        local round-trip midpoint), EMA-smoothed so one congested RTT
        doesn't yank the whole node's timeline. The GCS stores it on the
        node table; timeline assembly applies it so per-node timestamps
        compose cluster-wide (corrected = local_ts + offset)."""
        period = self.cfg.clock_sync_interval_s
        # first few rounds run quickly so a fresh node's timestamps are
        # correctable almost immediately, then settle to the period
        warmup = 3
        while True:
            try:
                # chaos: a dropped/slow heartbeat must perturb only this
                # round — the loop itself neither dies nor wedges
                if await failpoints.afire("raylet.heartbeat") == "drop":
                    raise ConnectionError("heartbeat dropped (failpoint)")
                t0 = time.time()
                reply = await self.gcs.call("ping", {}, timeout=5)
                t1 = time.time()
                sample = reply["time"] - (t0 + t1) / 2.0
                if self._clock_offset is None:
                    self._clock_offset = sample
                else:
                    self._clock_offset = (0.8 * self._clock_offset
                                          + 0.2 * sample)
                await self.gcs.call("report_clock_offset", {
                    "node_id": self.node_id,
                    "offset": self._clock_offset,
                    "rtt": t1 - t0,
                })
            except Exception:
                pass  # next round reconnects/retries
            if warmup > 0:
                warmup -= 1
                await asyncio.sleep(min(1.0, period))
            else:
                await asyncio.sleep(period)

    async def _on_gcs_reconnect(self):
        """A restarted GCS lost every per-connection subscription (and,
        if its journal was cold, this node's registration): re-register
        idempotently, re-subscribe, and push a fresh resource report so
        the cluster view heals without operator action (ref:
        gcs_redis_failure_detector.h restart path)."""
        try:
            await self.gcs.call("register_node", {
                "node_id": self.node_id,
                "address": self.server.address,
                "resources_total": self.resources.total.to_dict(),
                "resources_available": self.resources.available.to_dict(),
                "labels": self.labels,
                "slice_name": self.labels.get("slice_name", ""),
                "host_index": int(self.labels.get("host_index", 0)),
                "store_dir": self.store.dir,
                "transfer_address": self.transfer.address,
            })
            await self.gcs.call(
                "subscribe",
                {"channels": (["node", "object"] if self.syncer is not None
                              else ["resources", "node", "object"])})
            await self._report_resources()
            if self._clock_offset is not None:
                # a cold-journal GCS restart lost the node table entry's
                # offset: re-seed it so timelines stay correctable
                await self.gcs.call("report_clock_offset", {
                    "node_id": self.node_id,
                    "offset": self._clock_offset, "rtt": 0.0})
        except Exception:
            pass  # next retrying call reconnects and refires this hook

    # ----------------------------------------------------- memory pressure
    def _memory_fraction(self) -> Optional[float]:
        """Host memory usage fraction (ref: memory_monitor.h:52). Tests
        inject a fraction through ``memory_monitor_test_file``."""
        tf = self.cfg.memory_monitor_test_file
        if tf:
            try:
                with open(tf) as f:
                    return float(f.read().strip())
            except (OSError, ValueError):
                return None
        try:
            total = avail = None
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1])
                    if total is not None and avail is not None:
                        break
            if total and avail is not None:
                return 1.0 - avail / total
        except OSError:
            pass
        return None

    async def _memory_monitor_loop(self):
        """Kill workers under host memory pressure so retriable work is
        shed instead of the OS OOM-killer shooting randomly (ref:
        memory_monitor.h:52 + worker_killing_policy_retriable_fifo.h —
        newest non-actor lease dies first; its owner retries within the
        task's max_retries budget)."""
        period = self.cfg.memory_monitor_refresh_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            frac = self._memory_fraction()
            if frac is None or frac < self.cfg.memory_usage_threshold:
                continue
            leases = [l for l in self._leases.values()
                      if l.worker.actor_id is None and l.worker.alive]
            if not leases:
                continue
            victim = max(leases, key=lambda l: l.lease_id)
            worker = victim.worker
            try:
                os.kill(worker.pid, 9)
            except (ProcessLookupError, PermissionError):
                continue
            worker.alive = False
            try:
                await self.gcs.call("report_task_events", {"events": [{
                    "task_id": f"oom_kill_{worker.worker_id.hex()[:12]}",
                    "name": "WORKER_OOM_KILLED",
                    "state": "WORKER_OOM_KILLED",
                    "node_id": self.node_id,
                    "memory_fraction": frac,
                }]})
            except Exception:
                pass

    # ------------------------------------------------------- stall sentinel
    async def _task_watchdog_loop(self):
        """Hang detector for the compute plane: each tick probes this
        node's workers for RUNNING-task ages and completed-duration
        samples, flags tasks past an adaptive per-scheduling-class
        threshold (EMA of past durations x task_stall_ema_factor,
        floored at task_stall_threshold_s), captures the implicated
        worker's Python stack over its dump_stacks RPC, and emits a
        WARNING cluster event with the stack attached. The transfer
        stall check (watermark registry, no byte progress) rides the
        same tick."""
        period = self.cfg.task_watchdog_interval_s
        while True:
            await asyncio.sleep(period)
            try:
                await self._task_watchdog_tick()
            except Exception:
                pass  # a failed tick must never kill the watchdog

    async def _task_watchdog_tick(self):
        floor = self.cfg.task_stall_threshold_s
        factor = self.cfg.task_stall_ema_factor
        seen = set()
        for worker in list(self._workers.values()):
            if not worker.alive or worker.conn is None:
                continue
            try:
                client = await self._peer_client(worker.address)
                probe = await client.call("stall_probe", {}, timeout=5)
            except Exception:
                continue  # worker busy dying; health plane owns that
            for fn, dur in probe.get("completed", []):
                prev = self._class_ema.get(fn)
                self._class_ema[fn] = (dur if prev is None
                                       else 0.8 * prev + 0.2 * dur)
            for rec in probe.get("running", []):
                seen.add(rec["task_id"])
                ema = self._class_ema.get(rec["fn"])
                threshold = max(floor, ema * factor) if ema else floor
                if rec["age_s"] < threshold:
                    continue
                if rec["task_id"] in self._stalled_tasks:
                    # already alerted; keep the record's age fresh and
                    # re-check mitigation — the drain trigger is an age
                    # multiple the task may only now have reached (the
                    # hint/report half ran once at flag time: one stall
                    # event must fold exactly one straggler sample)
                    self._stalled_tasks[rec["task_id"]]["age_s"] = \
                        rec["age_s"]
                    await self._mitigate_stalled_task(worker, rec,
                                                      threshold,
                                                      first=False)
                    continue
                await self._flag_stalled_task(worker, rec, threshold)
        # a flagged task that is no longer RUNNING resolved itself
        for tid in list(self._stalled_tasks):
            if tid not in seen:
                self._stalled_tasks.pop(tid, None)
        if self.cfg.transfer_stall_timeout_s > 0:
            await self._check_transfer_stalls()
        await self._refresh_straggler_scores()

    async def _refresh_straggler_scores(self):
        """Pull the cluster straggler scores so _pick_node can
        deprioritize persistently-late nodes without a per-lease RPC."""
        if self.cfg.straggler_deprioritize_threshold <= 0:
            return
        try:
            rows = await self.gcs.call("straggler_scores", {}, timeout=5)
        except (asyncio.TimeoutError, ConnectionLost, RpcError, OSError):
            return  # stale scores beat a dead watchdog
        scores: Dict[str, float] = {}
        for row in rows or []:
            nid = row.get("node_id")
            if nid:
                scores[nid] = float(row.get("score", 0.0))
        self._straggler_scores = scores

    async def _flag_stalled_task(self, worker: WorkerHandle, rec: dict,
                                 threshold: float):
        stack = ""
        try:
            client = await self._peer_client(worker.address)
            dump = await client.call("dump_stacks", {}, timeout=5)
            for th in dump.get("threads", []):
                if th.get("task_id") == rec["task_id"]:
                    stack = th["stack"]
                    break
            else:
                # interpreter-level hang (e.g. a wedged C extension):
                # attach every thread rather than nothing
                stack = "\n".join(th["stack"]
                                  for th in dump.get("threads", []))
        except Exception:
            stack = "<stack capture failed: worker unreachable>"
        record = {
            "kind": "task_stall",
            "task_id": rec["task_id"],
            "fn": rec["fn"],
            "age_s": rec["age_s"],
            "threshold_s": threshold,
            "node_id": self.node_id.hex(),
            "worker_id": worker.worker_id.hex(),
            "pid": worker.pid,
            "stack": stack,
            "detected_at": time.time(),
        }
        self._stalled_tasks[rec["task_id"]] = record
        try:
            await self.gcs.call("report_event", {
                "source": "stall_sentinel",
                "severity": "WARNING",
                "message": (
                    f"task {rec['task_id'][:12]} ({rec['fn']}) stalled: "
                    f"RUNNING for {rec['age_s']:.1f}s on node "
                    f"{self.node_id.hex()[:12]} worker pid {worker.pid} "
                    f"(threshold {threshold:.1f}s)"),
                "fields": record,
            })
        except Exception:
            pass
        await self._mitigate_stalled_task(worker, rec, threshold)

    async def _mitigate_stalled_task(self, worker: WorkerHandle, rec: dict,
                                     threshold: float, first: bool = True):
        """Tail-tolerance reactions to a flagged stall: nudge the task's
        owner to hedge NOW (it only acts if the task opted into
        speculation), feed the lateness into the GCS straggler stats —
        both once, at flag time — and, re-checked every tick, drain a
        wedged non-actor worker so its owner's retry lands on a healthy
        one before a gang times out."""
        if first:
            lease = worker.lease
            owner = lease.owner_address if lease is not None else ""
            if owner:
                background(self._send_hedge_hint(owner, rec["task_id"]))
            background(self.gcs.call("report_straggler", {
                "node_id": self.node_id.hex(),
                "late_s": max(0.0, rec["age_s"] - threshold),
                "source": "task_watchdog",
            }, timeout=5))
        if (self.cfg.straggler_drain_enabled
                and worker.actor_id is None
                and worker.pid not in self._drained_workers
                and rec["age_s"] >= threshold
                * max(1.0, self.cfg.straggler_drain_after_factor)):
            self._drained_workers.add(worker.pid)
            try:
                os.kill(worker.pid, 9)
            except (ProcessLookupError, PermissionError):
                return
            worker.alive = False
            try:
                await self.gcs.call("report_event", {
                    "source": "stall_sentinel",
                    "severity": "WARNING",
                    "message": (
                        f"drained wedged worker pid {worker.pid} on node "
                        f"{self.node_id.hex()[:12]} (task "
                        f"{rec['task_id'][:12]} RUNNING {rec['age_s']:.1f}s"
                        f"); owner retry will resubmit elsewhere"),
                    "fields": {"kind": "worker_drained",
                               "task_id": rec["task_id"],
                               "node_id": self.node_id.hex(),
                               "pid": worker.pid},
                }, timeout=5)
            except (asyncio.TimeoutError, ConnectionLost, RpcError, OSError):
                pass  # the drain itself already happened; event is best-effort

    async def _send_hedge_hint(self, owner: str, task_id_hex: str):
        try:
            client = await self._peer_client(owner)
            await client.call("hedge_hint", {"task_id": task_id_hex},
                              timeout=5)
        except (asyncio.TimeoutError, ConnectionLost, RpcError, OSError):
            pass  # owner gone or pre-hedging: the hint is best-effort

    async def _check_transfer_stalls(self):
        stalls = self.store.stalled_pulls(self.cfg.transfer_stall_timeout_s)
        current = set()
        for s in stalls:
            oid = s["object_id"]
            current.add(oid)
            src = self._pull_sources.get(ObjectID.from_hex(oid))
            s.update({"kind": "transfer_stall",
                      "node_id": self.node_id.hex(),
                      "source_node": src.hex() if src else None,
                      "detected_at": time.time()})
            if oid in self._stalled_transfers:
                self._stalled_transfers[oid].update(s)
                continue
            self._stalled_transfers[oid] = s
            try:
                await self.gcs.call("report_event", {
                    "source": "stall_sentinel",
                    "severity": "WARNING",
                    "message": (
                        f"pull {oid[:12]} stalled on node "
                        f"{self.node_id.hex()[:12]}: no byte progress for "
                        f"{s['stalled_for_s']:.1f}s "
                        f"({s['watermark']}/{s['size']} bytes)"),
                    "fields": s,
                })
            except Exception:
                pass
        for oid in list(self._stalled_transfers):
            if oid not in current:
                self._stalled_transfers.pop(oid, None)

    async def handle_list_stalls(self, payload, conn):
        """This node's currently-flagged stalls (state api / cli health)."""
        return {
            "tasks": list(self._stalled_tasks.values()),
            "transfers": list(self._stalled_transfers.values()),
        }

    async def handle_dump_worker_stacks(self, payload, conn):
        """Fan dump_stacks across this node's live workers (cli stacks,
        GCS hung-collective forensics). Unreachable workers report an
        error entry instead of wedging the whole dump."""
        out = []
        for worker in list(self._workers.values()):
            if not worker.alive:
                continue
            try:
                client = await self._peer_client(worker.address)
                dump = await client.call("dump_stacks", {}, timeout=5)
            except Exception as e:
                dump = {"pid": worker.pid, "error": str(e) or repr(e)}
            dump["worker_id"] = worker.worker_id.hex()
            dump["node_id"] = self.node_id.hex()
            out.append(dump)
        return {"node_id": self.node_id.hex(), "workers": out}

    async def handle_profile_start_workers(self, payload, conn):
        """Fan profile_start (burst sampler at ``hz``) across this
        node's live workers. Per-worker failures are reported, not
        raised — one dead worker must not kill a cluster profile."""
        hz = float(payload.get("hz", 100.0))
        started, errors = 0, []
        for worker in list(self._workers.values()):
            if not worker.alive:
                continue
            try:
                client = await self._peer_client(worker.address)
                if await client.call("profile_start", {"hz": hz},
                                     timeout=5):
                    started += 1
            except Exception as e:
                errors.append({"pid": worker.pid,
                               "error": str(e) or repr(e)})
        return {"node_id": self.node_id.hex(), "started": started,
                "errors": errors}

    async def handle_profile_stop_workers(self, payload, conn):
        """Collect each worker's folded-stack snapshot (burst if one is
        running, else the ambient accumulation)."""
        out = []
        for worker in list(self._workers.values()):
            if not worker.alive:
                continue
            try:
                client = await self._peer_client(worker.address)
                snap = await client.call("profile_stop", {}, timeout=10)
            except Exception as e:
                snap = {"pid": worker.pid, "error": str(e) or repr(e),
                        "wall": {}, "cpu": {}, "samples": 0}
            snap["node_id"] = self.node_id.hex()
            out.append(snap)
        return {"node_id": self.node_id.hex(), "workers": out}

    async def handle_node_memory_report(self, payload, conn):
        """This node's memory-attribution inputs: the shared store's
        object inventory (directory scan — node-global in both index
        modes) plus every live worker's reference claims / heap stats."""
        workers = []
        for worker in list(self._workers.values()):
            if not worker.alive:
                continue
            try:
                client = await self._peer_client(worker.address)
                rep = await client.call("memory_report", {}, timeout=10)
            except Exception as e:
                rep = {"pid": worker.pid, "error": str(e) or repr(e),
                       "claims": {}}
            rep["worker_id"] = worker.worker_id.hex()
            workers.append(rep)
        return {
            "node_id": self.node_id.hex(),
            "store": self.store.usage_report(),
            "workers": workers,
        }

    async def stop(self):
        for task in list(self._token_conn_watchers.values()):
            task.cancel()
        self._token_conn_watchers.clear()
        for worker in self._workers.values():
            self._expected_exits.add(worker.pid)
            if worker.conn is not None:
                await worker.conn.push("shutdown", {})
        if self._blackbox is not None:
            self._blackbox.close(clean=True)
            self._blackbox = None
        if self.syncer is not None:
            self.syncer.stop()
        await self.server.stop()
        if self.transfer is not None:
            await self.transfer.stop()
        await self.gcs.close()
        for client in self._peer_clients.values():
            await client.close()
        await self._factory_teardown()
        for proc in self._subprocs:
            try:
                proc.terminate()
            except Exception:
                pass
        self._signal_factory_workers(15)
        deadline = time.monotonic() + 3
        for proc in self._subprocs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        await self._await_factory_workers(deadline)
        self._signal_factory_workers(9)

    async def die(self):
        """Abrupt node death for fault-injection tests (the cluster_utils
        `remove_node` analog): SIGKILL workers, drop connections ungracefully
        so the GCS health path — not a clean unregister — detects it."""
        if self.syncer is not None:
            # a "dead" node must stop gossiping, or it keeps re-opening
            # peer connections die() just severed
            self.syncer.stop()
        for proc in self._subprocs:
            try:
                proc.kill()
            except Exception:
                pass
        self._signal_factory_workers(9)
        if self._factory_proc is not None:
            try:
                self._factory_proc.kill()
            except Exception:
                pass
        # drop the GCS connection first — that's the death signal the GCS
        # health path turns into node-dead + object-lost events
        await self.gcs.close()
        await self.server.stop()
        if self.transfer is not None:
            await self.transfer.stop()
        for client in self._peer_clients.values():
            await client.close()

    def _on_remote_resources(self, payload):
        node_id, avail = payload["node_id"], payload["available"]
        if node_id == self.node_id:
            return
        entry = self._remote_nodes.get(node_id)
        if entry is not None:
            self._remote_nodes[node_id] = (entry[0], ResourceSet(avail))
            if self._pending_leases:  # capacity elsewhere: try spillback
                background(self._pump_pending())

    def _apply_peer_resources(self, node_hex: str,
                              available: dict) -> None:
        """Gossip-learned availability (syncer.py) feeding the same
        spillback view the hub pushes maintain. Availability ONLY:
        membership stays hub-authoritative (node channel), so a stale
        gossip entry can never resurrect a removed node into the
        spillback picker — unknown nodes are dropped here and evicted
        from the gossip view."""
        node_id = NodeID.from_hex(node_hex)
        entry = self._remote_nodes.get(node_id)
        if entry is None:
            if self.syncer is not None and node_id != self.node_id:
                self.syncer.evict(node_hex)
            return
        self._remote_nodes[node_id] = (entry[0], ResourceSet(available))
        if self._pending_leases:
            background(self._pump_pending())

    async def handle_syncer_sync(self, payload, conn):
        if self.syncer is None:
            return {"entries": {}, "want": []}
        return await self.syncer.handle_sync(payload)

    async def handle_syncer_push(self, payload, conn):
        if self.syncer is None:
            return 0
        return await self.syncer.handle_push(payload)

    async def handle_health(self, payload, conn):
        """Target of the GCS's ACTIVE health probe (gcs.py
        _node_health_loop; ref: gcs_health_check_manager.h). Answering
        requires THIS event loop to turn — a SIGSTOP'd or livelocked
        raylet keeps its socket open but fails the probe."""
        return True

    def _on_node_event(self, payload):
        if payload["event"] == "added":
            info = payload["node"]
            if info.node_id != self.node_id:
                self._remote_nodes[info.node_id] = (info.address, ResourceSet(info.resources_available))
                self._node_labels[info.node_id] = dict(info.labels or {})
                # a re-registered node is alive again by hub decree
                self._dead_node_hexes.pop(info.node_id.hex(), None)
                if self._pending_leases:  # a new node may fit queued work
                    background(self._pump_pending())
        elif payload["event"] == "removed":
            node_id = payload.get("node_id")
            self._remote_nodes.pop(node_id, None)
            if node_id is not None:
                self._dead_node_hexes[node_id.hex()] = None
                while len(self._dead_node_hexes) > 4096:
                    self._dead_node_hexes.popitem(last=False)
            if self.syncer is not None and node_id is not None:
                self.syncer.evict(node_id.hex())

    async def _report_resources(self):
        """Fire-and-forget availability report. Never awaited into the lease
        grant path — a lost frame must not stall granting. The sequence
        number lets the GCS drop late/stale reports (absolute values +
        last-writer-wins needs an order)."""
        self._resource_seq += 1
        payload = {
            "node_id": self.node_id,
            "available": self.resources.available.to_dict(),
            "seq": self._resource_seq,
            # queued lease shapes: the autoscaler's scale-up signal
            "pending": [p.resources.to_dict()
                        for p in self._pending_leases],
        }
        if self.syncer is not None:
            self.syncer.local_update(payload["available"],
                                     payload["pending"], payload["seq"])

        async def _send():
            try:
                await self.gcs.call_retrying("report_resources", payload,
                                             attempts=3, per_try_timeout=2.0)
            except Exception:
                pass

        background(_send())

    # ---------------------------------------------------------- worker pool
    def _spawn_worker(self) -> None:
        self._starting += 1
        env, log_path = self._worker_env()
        if self.cfg.worker_factory_enabled:
            background(self._spawn_via_factory(env, log_path))
        else:
            self._popen_worker(env, log_path)

    def _worker_env(self) -> tuple:
        env = dict(os.environ)
        # propagate the driver's import surface so by-reference pickles resolve
        # (the minimal working_dir runtime-env; ref: _private/runtime_env/working_dir.py)
        extra_path = [p for p in sys.path if p] + [os.getcwd()]
        env["PYTHONPATH"] = os.pathsep.join(
            extra_path + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        env["RAY_TPU_SESSION"] = self.session_name
        env["RAY_TPU_RAYLET_SOCKET"] = self.socket_path
        env["RAY_TPU_GCS_SOCKET"] = self.gcs_address
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_STORE_DIR"] = self.store.dir
        # Pool workers run CPU-only jax: skip the TPU PJRT bootstrap entirely
        # (it imports jax at interpreter start, ~2s). FORCE the pin — a
        # driver launched under a sitecustomize that exports
        # JAX_PLATFORMS="axon,cpu" would otherwise leak a device-plane
        # platform into workers whose tunnel env we strip below, leaving
        # jax pointed at a backend that cannot register (worker crash on
        # first jax import). TPU gang workers reclaim the device plane
        # explicitly (train/worker_group.py _maybe_init_jax_distributed).
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        # worker stdout/stderr land in per-worker session log files (the
        # reference's log_monitor capture; surfaced via the state API's
        # list_logs/get_log raylet RPCs)
        log_dir = session_log_dir(self.session_name)
        os.makedirs(log_dir, exist_ok=True)
        # redirected-to-file stdout is block-buffered by default: a live
        # pooled worker's prints would sit in the 8KB buffer forever
        env["PYTHONUNBUFFERED"] = "1"
        self._worker_seq += 1
        log_path = os.path.join(
            log_dir, f"worker-{self.node_id.hex()[:8]}-{self._worker_seq}.log")
        return env, log_path

    def _popen_worker(self, env: dict, log_path: str) -> None:
        log_file = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env=env,
            stdout=log_file,
            stderr=log_file,
            start_new_session=True,
        )
        log_file.close()  # the child holds its own fd
        self._subprocs.append(proc)

    # ---------------------------------------------- worker factory (fork)
    # A cold worker pays ~0.7 s of interpreter+import startup; the factory
    # (worker_factory.py) imports once and forks per worker, which is what
    # makes envelope-depth actor counts (1k+ live actors on one host)
    # reachable (ref: worker_pool.h prestart amortization).
    async def _spawn_via_factory(self, env: dict, log_path: str) -> None:
        try:
            pid = await self._factory_request(
                {"cmd": "spawn", "log_path": log_path, "env": env})
            self._factory_pids.append(pid)
        except Exception as e:
            # factory unavailable (failed to start, died mid-request):
            # cold-start this worker and let the next spawn retry the
            # factory from scratch
            print(f"[raylet] worker factory spawn failed "
                  f"({type(e).__name__}: {e}); falling back to cold start",
                  file=sys.stderr)
            await self._factory_teardown()
            try:
                self._popen_worker(env, log_path)
            except Exception:
                self._starting = max(0, self._starting - 1)

    async def _factory_request(self, req: dict) -> int:
        async with self._factory_lock:
            if self._factory_writer is None:
                await self._factory_start_locked()
            writer = self._factory_writer
            reader = self._factory_reader
            writer.write(json.dumps(req).encode() + b"\n")
            await writer.drain()
            line = await asyncio.wait_for(
                reader.readline(), self.cfg.worker_startup_timeout_s)
        if not line:
            raise ConnectionLost("worker factory closed its socket")
        reply = json.loads(line)
        if "error" in reply:
            raise RuntimeError(f"worker factory: {reply['error']}")
        pid = reply.get("pid")
        if not isinstance(pid, int) or pid <= 0:
            # never let a malformed reply become pid 0/-1 — os.kill(0)
            # signals this whole process group at shutdown
            raise RuntimeError(f"worker factory: bad spawn reply {reply!r}")
        return pid

    async def _factory_start_locked(self) -> None:
        sock_path = os.path.join(
            session_log_dir(self.session_name),
            f"factory-{self.node_id.hex()[:8]}.sock")
        os.makedirs(os.path.dirname(sock_path), exist_ok=True)
        env, _ = self._worker_env()
        env["RAY_TPU_FACTORY_SOCKET"] = sock_path
        log_path = os.path.join(session_log_dir(self.session_name),
                                f"factory-{self.node_id.hex()[:8]}.log")
        log_file = open(log_path, "ab")
        self._factory_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_factory"],
            env=env, stdout=log_file, stderr=log_file)
        log_file.close()
        # the factory binds its socket only after the worker stack is
        # imported, so connect-success == ready
        deadline = time.monotonic() + self.cfg.worker_startup_timeout_s
        while True:
            try:
                reader, writer = await asyncio.open_unix_connection(sock_path)
                break
            except (FileNotFoundError, ConnectionRefusedError, OSError) as e:
                if (time.monotonic() > deadline
                        or self._factory_proc.poll() is not None):
                    proc, self._factory_proc = self._factory_proc, None
                    try:
                        proc.kill()
                    except Exception:
                        pass
                    raise TimeoutError(
                        "worker factory did not come up") from e
                await asyncio.sleep(0.05)
        self._factory_reader, self._factory_writer = reader, writer

    async def _factory_teardown(self) -> None:
        async with self._factory_lock:
            if self._factory_writer is not None:
                try:
                    self._factory_writer.write(b'{"cmd": "exit"}\n')
                    await self._factory_writer.drain()
                    self._factory_writer.close()
                except Exception:
                    pass
                self._factory_reader = self._factory_writer = None
            if self._factory_proc is not None:
                proc, self._factory_proc = self._factory_proc, None
                try:
                    proc.terminate()
                    await asyncio.get_event_loop().run_in_executor(
                        None, lambda: proc.wait(timeout=3))
                except Exception:
                    try:
                        proc.kill()
                    except Exception:
                        pass

    def _signal_factory_workers(self, sig: int) -> None:
        for pid in list(self._factory_pids):
            try:
                os.kill(pid, sig)
            except ProcessLookupError:
                self._factory_pids.remove(pid)
            except PermissionError:
                pass

    async def _await_factory_workers(self, deadline: float) -> None:
        """Give SIGTERM'd factory workers the same grace window Popen
        workers get before the SIGKILL pass (they are the factory's
        children, not ours — no waitpid, poll liveness instead).
        Async: this runs on the raylet's io loop during stop(), and a
        sleeping poll there would freeze every other connection for the
        full grace window (graftlint: blocking-call-on-loop)."""
        while self._factory_pids and time.monotonic() < deadline:
            for pid in list(self._factory_pids):
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    self._factory_pids.remove(pid)
                except PermissionError:
                    pass
            if self._factory_pids:
                await asyncio.sleep(0.05)

    async def handle_register_worker(self, payload, conn):
        worker = WorkerHandle(
            worker_id=payload["worker_id"],
            pid=payload["pid"],
            address=payload["address"],
            conn=conn,
        )
        self._workers[worker.worker_id] = worker
        self._worker_conns[conn] = worker.worker_id
        self._starting = max(0, self._starting - 1)
        self._idle.append(worker)
        await self._pump_pending()
        return {"node_id": self.node_id, "session": self.session_name}

    async def handle_worker_blocked(self, payload, conn):
        """The worker's current task is blocked resolving objects: hand
        its CPU share back so other work can run — withholding it
        deadlocks dependency chains once every worker waits (ref:
        node_manager.cc HandleNotifyDirectCallTaskBlocked →
        ReleaseCpuResourcesFromBlockedWorker)."""
        worker = self._workers.get(payload["worker_id"])
        if worker is None or worker.lease is None:
            return False
        lease = worker.lease
        if lease.blocked_cpu is not None:
            return True  # already released (re-entrant block)
        cpu = lease.resources.get("CPU", 0.0)
        if cpu <= 0:
            return True
        part = ResourceSet({"CPU": cpu})
        lease.blocked_cpu = part
        lease.resources = ResourceSet(
            {k: v for k, v in lease.resources.to_dict().items()
             if k != "CPU"})
        if lease.pg_key is not None:
            bundle = self._pg_bundles.get(lease.pg_key)
            if bundle is not None:
                bundle.release(part)
        else:
            self.resources.release(part)
        await self._report_resources()
        await self._pump_pending()
        return True

    async def handle_worker_unblocked(self, payload, conn):
        """Blocked worker resumed: re-take its CPU (forced — transient
        oversubscription beats starving the resumed task, matching the
        reference's ReturnCpuResourcesToUnblockedWorker)."""
        worker = self._workers.get(payload["worker_id"])
        if worker is None or worker.lease is None:
            return False
        lease = worker.lease
        part, lease.blocked_cpu = lease.blocked_cpu, None
        if part is None:
            return True
        if lease.pg_key is not None:
            bundle = self._pg_bundles.get(lease.pg_key)
            if bundle is not None:
                bundle.force_allocate(part)
        else:
            self.resources.force_allocate(part)
        lease.resources.add(part)
        await self._report_resources()
        return True

    async def _blackbox_worker_gone(self, worker: "WorkerHandle"):
        """Black-box disposition for a vanished worker: an exit this
        raylet ORDERED (shutdown push, drain kill marked expected)
        discards the flight file quietly; an unexpected death promotes
        it to a crash bundle — carrying the worker's own last-flushed
        in-flight tasks — and reports the crash to the GCS incident
        log. SIGKILL leaves no in-process hook, so the survivor doing
        the sweep is the only way those deaths get flight data."""
        if not self.cfg.blackbox_enabled:
            return
        from . import blackbox

        if worker.pid in self._expected_exits:
            self._expected_exits.discard(worker.pid)
            blackbox.discard_flight(self._session_dir, worker.pid)
            return
        reason = ("drain_kill" if worker.pid in self._drained_workers
                  else "worker_disconnect")
        try:
            promoted = blackbox.sweep(
                self._session_dir, reason=reason,
                bundled_by=f"raylet-{self.node_id.hex()[:12]}",
                pids=[worker.pid])
        except Exception:  # graftlint: ignore[swallow] — a failed sweep
            return  # must not break disconnect handling
        for snap in promoted:
            try:
                await self.gcs.call("report_crash", {
                    "role": snap.get("role", "worker"),
                    "pid": worker.pid,
                    "node_id": self.node_id.hex(),
                    "reason": reason,
                    "signal": snap.get("signal_name"),
                    "bundle_path": snap.get("path"),
                    "inflight": (snap.get("inflight") or [])[:5],
                }, timeout=5)
            except Exception:  # graftlint: ignore[swallow] — the bundle
                pass  # is on disk; losing the GCS event is tolerable

    async def _on_disconnect(self, conn):
        # reap exited worker subprocesses and drop them from tracking (dead
        # workers would otherwise linger as zombies until node stop)
        self._subprocs = [p for p in self._subprocs if p.poll() is None]
        worker_id = self._worker_conns.pop(conn, None)
        if worker_id is None:
            return
        worker = self._workers.pop(worker_id, None)
        if worker is None:
            return
        worker.alive = False
        await self._blackbox_worker_gone(worker)
        # a gone worker's pid may be recycled by the kernel — never keep
        # it on the factory kill list
        try:
            self._factory_pids.remove(worker.pid)
        except ValueError:
            pass
        if worker in self._idle:
            self._idle.remove(worker)
        if worker.lease is not None:
            lease = worker.lease
            self._forget_rid(lease.lease_id)
            self._release_lease_resources(lease)
            self._leases.pop(lease.lease_id, None)
            await self._report_resources()
        if worker.actor_id is not None:
            try:
                await self.gcs.call("actor_failed", {
                    "actor_id": worker.actor_id,
                    "cause": f"worker process {worker.pid} died",
                })
            except Exception:
                pass
        # leases the dead process OWNED (fast lanes it opened for its own
        # subtasks) must be reaped too, or their resources leak forever —
        # observed: a killed SplitCoordinator's 1-CPU lane lease wedging
        # every later data pipeline on the node (ref: the reference's
        # per-owner lease cleanup on worker death,
        # node_manager.cc HandleUnexpectedWorkerFailure)
        orphaned = [l for l in self._leases.values()
                    if l.owner_address == worker.address]
        for lease in orphaned:
            self._leases.pop(lease.lease_id, None)
            self._forget_rid(lease.lease_id)
            self._release_lease_resources(lease)
            held = lease.worker
            held.lease = None
            # disconnect rather than reuse: the orphaned worker may have
            # a lane-serve thread still polling the dead owner's ring
            held.alive = False
            self._expected_exits.add(held.pid)
            if held.conn is not None:
                try:
                    await held.conn.push("shutdown", {})
                except Exception:
                    pass
        if orphaned:
            await self._report_resources()
        await self._pump_pending()

    async def _pop_worker(self, dedicated: bool = False) -> Optional[WorkerHandle]:
        while self._idle:
            worker = self._idle.pop()
            if worker.alive:
                return worker
        if dedicated:
            # an actor pins its worker for life, so the pool soft limit
            # must not gate it — the limit sizes the REUSABLE pool, and a
            # pinned worker never returns to it (ref: worker_pool.h —
            # dedicated workers bypass the soft cap). Spawns are bounded
            # by actual dedicated demand (this request + queued actor
            # leases) and burst-throttled so 1k queued creations don't
            # fork-storm — without the demand bound, every pump pass
            # during one worker's startup window would fork another.
            demand = 1 + sum(
                1 for p in self._pending_leases
                if p.payload.get("actor_id") is not None
                and not p.future.done())
            if self._starting < min(self.cfg.worker_spawn_burst, demand):
                self._spawn_worker()
            return None
        # dep-blocked workers released their CPU but still sit in the
        # pool: they must not count against the cap, or the freed CPU is
        # ungrantable (no worker to run on) and dependency chains starve
        # (ref: worker_pool.h soft-limit exempting blocked workers)
        blocked = sum(1 for l in self._leases.values()
                      if l.blocked_cpu is not None)
        if len(self._workers) + self._starting - blocked < self.max_workers:
            self._spawn_worker()
        return None

    # -------------------------------------------------------------- leasing
    async def handle_request_worker_lease(self, payload, conn):
        """Grant a worker lease, spill to a remote node, or queue.

        payload: {resources, strategy, owner_address, actor_id?, pg?}
        reply:   {granted: bool, worker_address, lease_id, node_id}
               | {retry_at: (node_id, address)}
        """
        # a raise here rides the ERROR reply into the core_worker's
        # lease pipeline and lands in the task's return objects —
        # chaos asserts the driver's ray.get names this site
        await failpoints.afire("raylet.lease.grant")
        payload["_conn"] = conn  # reclaim push channel for lane leases
        rid = payload.get("request_id")
        if rid is not None:
            cached = self._lease_rid_grants.get(rid)
            if cached is not None and cached["lease_id"] in self._leases:
                return cached  # duplicate of an already-granted request
            pending = self._lease_rid_pending.get(rid)
            if pending is not None:
                # duplicate of a queued request; also covers the race where
                # the future resolved but the original handler hasn't
                # recorded the grant yet (awaiting a done future is a no-op)
                return await pending
        resources = ResourceSet(payload.get("resources", {}))
        strategy = payload.get("strategy")
        target = (None if payload.get("no_spill")
                  else self._pick_node(resources, strategy,
                                       avoid=payload.get("avoid_nodes")))
        if target is not None and target != self.node_id:
            addr, _ = self._remote_nodes[target]
            return {"granted": False, "retry_at": (target, addr)}
        if self._pg_key(strategy) is not None:
            pg_id = self._pg_key(strategy)[0]
            if not any(k[0] == pg_id for k in self._pg_bundles):
                raise ValueError("placement group bundle not reserved on this node")
        grant = await self._try_grant(resources, payload)
        if grant is not None:
            self._record_rid_grant(rid, grant)
            return grant
        # queue until a worker/resources free up; report immediately so
        # the GCS (and the autoscaler watching it) sees the new demand
        fut = asyncio.get_event_loop().create_future()
        self._pending_leases.append(
            _PendingLease(payload, fut, resources,
                          queued_at=time.monotonic()))
        await self._report_resources()
        if rid is not None:
            self._lease_rid_pending[rid] = fut
        try:
            grant = await fut
        finally:
            if self._lease_rid_pending.get(rid) is fut:
                self._lease_rid_pending.pop(rid, None)
        self._record_rid_grant(rid, grant)
        return grant

    def _record_rid_grant(self, rid: Optional[str], grant: dict) -> None:
        if rid is not None and grant.get("granted"):
            self._lease_rid_grants[rid] = grant
            self._lease_id_to_rid[grant["lease_id"]] = rid

    def _forget_rid(self, lease_id: int) -> None:
        rid = self._lease_id_to_rid.pop(lease_id, None)
        if rid is not None:
            self._lease_rid_grants.pop(rid, None)

    def _pg_key(self, strategy) -> Optional[tuple]:
        if isinstance(strategy, PlacementGroupSchedulingStrategy) and strategy.placement_group_id:
            return (strategy.placement_group_id, strategy.placement_group_bundle_index)
        return None

    def _pg_allocate(self, key: tuple, resources: ResourceSet) -> Optional[tuple]:
        """Allocate the lease's resources inside a reserved bundle; a -1 index
        is a wildcard over this node's bundles of that PG (reference
        semantics: `bundle_index=-1` = any bundle)."""
        pg_id, idx = key
        if idx >= 0:
            bundle = self._pg_bundles.get(key)
            if bundle is not None and bundle.try_allocate(resources):
                return key
            return None
        for k, bundle in self._pg_bundles.items():
            if k[0] == pg_id and bundle.try_allocate(resources):
                return k
        return None

    def _strategy_allows_local(self, strategy) -> bool:
        """Hard label expressions must hold for THIS node before a local
        grant; otherwise the lease stays queued for spillback/arrival."""
        if isinstance(strategy, NodeLabelSchedulingStrategy):
            return label_expr_matches(
                self._node_labels.get(self.node_id, dict(self.labels)),
                strategy.hard)
        return True

    async def _try_grant(self, resources: ResourceSet, payload):
        if not self._strategy_allows_local(payload.get("strategy")):
            return None
        pg_key = self._pg_key(payload.get("strategy"))
        alloc_key = None
        if pg_key is not None:
            # bundle resources were deducted from the node at reservation;
            # the lease draws from the bundle's own pool
            alloc_key = self._pg_allocate(pg_key, resources)
            if alloc_key is None:
                return None
        elif not self.resources.try_allocate(resources):
            return None
        worker = await self._pop_worker(
            dedicated=payload.get("actor_id") is not None)
        if worker is None:
            if alloc_key is not None:
                self._pg_bundles[alloc_key].release(resources)
            else:
                self.resources.release(resources)
            return None
        chips = self._allocate_chips(resources.get("TPU", 0.0))
        if chips is None:
            # resource math admitted the lease but chips are exhausted
            # (should not diverge; defensive): give everything back
            if alloc_key is not None:
                self._pg_bundles[alloc_key].release(resources)
            else:
                self.resources.release(resources)
            self._return_worker_to_pool(worker)
            return None
        lease = Lease(self._next_lease_id, worker, resources,
                      payload.get("owner_address", ""), pg_key=alloc_key,
                      lane=bool(payload.get("lane")),
                      conn=payload.get("_conn"), chips=chips)
        self._next_lease_id += 1
        worker.lease = lease
        if payload.get("actor_id") is not None:
            worker.actor_id = payload["actor_id"]
        self._leases[lease.lease_id] = lease
        await self._report_resources()
        return {
            "granted": True,
            "worker_address": worker.address,
            "worker_id": worker.worker_id,
            "lease_id": lease.lease_id,
            "node_id": self.node_id,
            # the leased worker's chip visibility set (TPU leases only)
            "chip_ids": sorted(i for i, _ in lease.chips),
        }

    async def handle_cancel_lease_request(self, payload, conn):
        """Fail a queued lease request for a cancelled task so the owner's
        submit path unblocks (ref: node_manager.cc HandleCancelWorkerLease).
        Races with a grant are benign: the owner re-checks its cancel flag
        before pushing the task and returns the worker unused."""
        from .. import exceptions as exc

        task_id = payload["task_id"]
        hit = False
        for pending in self._pending_leases[:]:
            if pending.payload.get("task_id") == task_id and not pending.future.done():
                pending.future.set_exception(
                    exc.TaskCancelledError("lease request cancelled"))
                hit = True
        return hit

    async def handle_list_logs(self, payload, conn):
        """THIS node's captured worker logs (log-monitor surface). The
        session log dir is shared by co-hosted raylets, so filter to our
        own node-id prefix."""
        prefix = f"worker-{self.node_id.hex()[:8]}-"
        try:
            return sorted(n for n in os.listdir(
                session_log_dir(self.session_name))
                if n.startswith(prefix))
        except FileNotFoundError:
            return []

    async def handle_tail_log(self, payload, conn):
        """Last ``tail_bytes`` of one captured log (basename only — no
        path traversal out of the session log dir)."""
        name = os.path.basename(payload["name"])
        tail_bytes = int(payload.get("tail_bytes", 1 << 16))
        path = os.path.join(session_log_dir(self.session_name), name)
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read()
        except OSError:  # missing, or '.'/'..' resolving to a directory
            return b""

    async def handle_return_worker(self, payload, conn):
        lease = self._leases.pop(payload["lease_id"], None)
        if lease is None:
            return False
        self._forget_rid(lease.lease_id)
        self._release_lease_resources(lease)
        worker = lease.worker
        worker.lease = None
        if payload.get("disconnect_worker"):
            worker.alive = False
            self._expected_exits.add(worker.pid)
            if worker.conn is not None:
                await worker.conn.push("shutdown", {})
        elif worker.alive and worker.actor_id is None:
            worker.idle_since = time.monotonic()
            self._idle.append(worker)
        await self._report_resources()
        await self._pump_pending()
        return True

    async def _pump_pending(self):
        """Grant queued lease requests as capacity frees up.

        Non-reentrant: _try_grant awaits, during which new requests may queue
        or another pump may trigger — a flag serializes pumps and a re-run bit
        picks up arrivals, so no request is double-granted or dropped.
        """
        if getattr(self, "_pumping", False):
            self._pump_again = True
            return
        self._pumping = True
        try:
            rerun = True
            while rerun:
                self._pump_again = False
                i = 0
                while i < len(self._pending_leases):
                    pending = self._pending_leases[i]
                    if pending.future.done():
                        self._pending_leases.pop(i)
                        continue
                    grant = await self._try_grant(pending.resources, pending.payload)
                    if grant is None:
                        await self._request_lane_reclaims()
                        # spillback: a node that joined (autoscaler) or
                        # freed up since this lease queued may fit it
                        # now. Damped: never for no_spill leases (chain
                        # cap reached) and only after a settle period so
                        # two saturated raylets with stale views of each
                        # other don't bounce a lease back and forth.
                        if (pending.payload.get("no_spill")
                                or time.monotonic() - pending.queued_at
                                < self.cfg.lease_spill_min_queue_s):
                            i += 1
                            continue
                        target = self._pick_node(
                            pending.resources,
                            pending.payload.get("strategy"),
                            avoid=pending.payload.get("avoid_nodes"))
                        if (target is not None and target != self.node_id
                                and target in self._remote_nodes):
                            addr, _ = self._remote_nodes[target]
                            self._pending_leases.pop(i)
                            if not pending.future.done():
                                pending.future.set_result(
                                    {"granted": False,
                                     "retry_at": (target, addr)})
                            continue
                        i += 1
                        continue
                    self._pending_leases.pop(i)
                    if pending.future.done():  # caller gave up mid-grant
                        await self.handle_return_worker(
                            {"lease_id": grant["lease_id"]}, None)
                    else:
                        pending.future.set_result(grant)
                rerun = self._pump_again
        finally:
            self._pumping = False

    # ------------------------------------------------------ scheduling policy
    def _pick_node(self, resources: ResourceSet, strategy,
                   avoid: Optional[List[str]] = None) -> Optional[NodeID]:
        """Returns the node the lease should run on; None means "queue here".

        Hybrid default (ref: hybrid_scheduling_policy.h:50): prefer local while
        local utilization < threshold; otherwise least-utilized feasible node.

        Tail tolerance: nodes in ``avoid`` (a hedge steering off its
        primary's node) and nodes whose straggler score crossed
        ``straggler_deprioritize_threshold`` are soft-excluded — skipped
        while any clean feasible node exists, used as a last resort
        rather than failing the lease.
        """
        bad = set(avoid or ())
        thresh = self.cfg.straggler_deprioritize_threshold
        if thresh > 0:
            for nhex, score in self._straggler_scores.items():
                if score >= thresh:
                    bad.add(nhex)

        def _prefer(feasible):
            good = [(nid, a) for nid, a in feasible
                    if nid.hex() not in bad]
            return good or feasible

        if isinstance(strategy, NodeAffinitySchedulingStrategy) and strategy.node_id:
            target = NodeID.from_hex(strategy.node_id)
            if target == self.node_id or target in self._remote_nodes:
                return target
            if not strategy.soft:
                raise ValueError(f"node {strategy.node_id} not available (hard affinity)")
            return None
        if self._pg_key(strategy) is not None:
            return self.node_id  # caller already directed to the bundle's node
        local_fits = resources.fits(self.resources.available)
        if isinstance(strategy, NodeLabelSchedulingStrategy):
            # hard expressions gate feasibility; soft ones rank the
            # feasible set (ref: node_label_scheduling_policy.h + A.2)
            def _labels(nid):
                return self._node_labels.get(nid, {})

            candidates = [(self.node_id, self.resources.available)] + [
                (nid, avail) for nid, (_, avail) in self._remote_nodes.items()
            ]
            feasible = [
                (nid, a) for nid, a in candidates
                if resources.fits(a)
                and label_expr_matches(_labels(nid), strategy.hard)]
            if not feasible:
                return None  # queue: a matching node may join/free up
            soft_ok = [(nid, a) for nid, a in feasible
                       if label_expr_matches(_labels(nid), strategy.soft)]
            pool = _prefer(soft_ok or feasible)
            for nid, _ in pool:
                if nid == self.node_id:
                    return nid  # local preferred within the match set
            return pool[0][0]
        if isinstance(strategy, SpreadSchedulingStrategy):
            candidates = [(self.node_id, self.resources.available)] + [
                (nid, avail) for nid, (_, avail) in self._remote_nodes.items()
            ]
            feasible = [(nid, a) for nid, a in candidates if resources.fits(a)]
            if not feasible:
                return None
            feasible = _prefer(feasible)
            self._spill_rr += 1
            return feasible[self._spill_rr % len(feasible)][0]
        # default / hybrid
        local_bad = self.node_id.hex() in bad
        if (local_fits and not local_bad
                and self.resources.utilization()
                < self.cfg.scheduler_spread_threshold):
            return self.node_id
        best, best_util = None, None
        best_bad = None  # least-utilized feasible node among the avoided
        for nid, (_, avail) in self._remote_nodes.items():
            if resources.fits(avail):
                util = 1.0 - min(
                    (avail.get(k, 0.0) / v) for k, v in resources.res.items() if v > 0
                ) if resources.res else 0.0
                if nid.hex() in bad:
                    if best_bad is None:
                        best_bad = nid
                    continue
                if best_util is None or util < best_util:
                    best, best_util = nid, util
        if (local_fits and not local_bad
                and (best is None
                     or self.resources.utilization() <= (best_util or 1.0))):
            return self.node_id
        if best is not None:
            return best
        # only avoided/straggler options remain: degrade rather than fail
        if local_bad and best_bad is not None:
            return best_bad
        return self.node_id if local_fits else best_bad

    # ------------------------------------------------- placement group bundles
    def _release_lease_resources(self, lease: Lease) -> None:
        """Return a finished lease's resources to the bundle it drew from, or
        to the node pool. A canceled bundle already released its whole
        reservation, so its leases return nothing."""
        self._release_chips(lease.chips)
        lease.chips = []
        if lease.pg_key is not None:
            bundle = self._pg_bundles.get(lease.pg_key)
            if bundle is not None:
                bundle.release(lease.resources)
            return
        self.resources.release(lease.resources)

    # -------------------------------------------------- per-lease TPU chips
    def _allocate_chips(self, amount: float) -> Optional[List[tuple]]:
        """Assign physical chips to a TPU lease: whole units take
        exclusive free chips; a fractional tail bin-packs onto the most-
        loaded chip it still fits (so shards share one chip, not many).
        Returns [(chip_id, fraction)], [] for non-TPU leases, None when
        chip accounting can't satisfy the amount."""
        if amount <= 0 or not self._chip_used:
            return []
        eps = 1e-9
        whole = int(amount + eps)
        frac = amount - whole
        alloc: List[tuple] = []
        free = [i for i, u in enumerate(self._chip_used) if u <= eps]
        if len(free) < whole:
            return None
        for i in free[:whole]:
            alloc.append((i, 1.0))
        if frac > eps:
            taken = {i for i, _ in alloc}
            best = None
            for i, used in enumerate(self._chip_used):
                if i in taken or used + frac > 1.0 + eps:
                    continue
                if used > eps and (best is None
                                   or used > self._chip_used[best]):
                    best = i  # most-loaded shared chip that still fits
            if best is None:  # no partially-used chip fits: take a free one
                rest = free[whole:]
                if not rest:
                    return None  # nothing reserved yet: clean failure
                best = rest[0]
            alloc.append((best, frac))
        for i, f in alloc:
            self._chip_used[i] += f
        return alloc

    def _release_chips(self, chips: List[tuple]) -> None:
        for i, f in chips:
            if 0 <= i < len(self._chip_used):
                self._chip_used[i] = max(0.0, self._chip_used[i] - f)

    def _return_worker_to_pool(self, worker: WorkerHandle) -> None:
        worker.lease = None
        if worker.alive and worker.actor_id is None:
            worker.idle_since = time.monotonic()
            self._idle.append(worker)

    async def _request_lane_reclaims(self) -> None:
        """Pending demand (queued lease / PG reservation) cannot fit:
        ask fast-lane owners to hand back idle lanes. Rate-limited per
        lease; actual release is the owner's call (a busy lane stays)."""
        now = time.monotonic()
        for lease in self._leases.values():
            if not lease.lane or lease.conn is None:
                continue
            if now - lease.reclaim_requested_at < 2.0:
                continue
            lease.reclaim_requested_at = now
            try:
                await lease.conn.push("reclaim_lease",
                                      {"lease_id": lease.lease_id})
            except Exception:
                pass

    async def handle_reserve_bundle(self, payload, conn):
        """Two-phase commit, phase 1: reserve resources for a PG bundle
        (ref: placement_group_resource_manager.h)."""
        resources = ResourceSet(payload["resources"])
        key = (payload["pg_id"], payload["bundle_index"])
        if key in self._pg_bundles:
            return True
        if not self.resources.try_allocate(resources):
            # idle fast lanes may be squatting on exactly this capacity;
            # the GCS retries the reservation after the release lands
            await self._request_lane_reclaims()
            return False
        self._pg_bundles[key] = NodeResources(resources.to_dict())
        await self._report_resources()
        return True

    async def handle_commit_bundle(self, payload, conn):
        return (payload["pg_id"], payload["bundle_index"]) in self._pg_bundles

    async def handle_cancel_bundle(self, payload, conn):
        key = (payload["pg_id"], payload["bundle_index"])
        reserved = self._pg_bundles.pop(key, None)
        if reserved is None:
            return True
        # evict leases living inside the bundle: their workers are killed so
        # PG removal reclaims the processes (ref: gcs_placement_group_scheduler
        # DestroyPlacementGroupCommittedBundleResources kills bundle workers)
        for lease in list(self._leases.values()):
            if lease.pg_key == key:
                self._leases.pop(lease.lease_id, None)
                self._forget_rid(lease.lease_id)
                # bundle resources die with the reservation below, but
                # chip accounting is node-global and must be returned
                self._release_chips(lease.chips)
                lease.chips = []
                worker = lease.worker
                worker.lease = None
                worker.alive = False
                self._expected_exits.add(worker.pid)
                if worker.conn is not None:
                    await worker.conn.push("shutdown", {})
        self.resources.release(reserved.total)
        # queued leases waiting on this PG with no bundle left here would wait
        # forever: fail them so the submitter re-resolves (and learns of
        # removal from the GCS directory)
        if not any(k[0] == key[0] for k in self._pg_bundles):
            for pending in self._pending_leases[:]:
                pgk = self._pg_key(pending.payload.get("strategy"))
                if pgk is not None and pgk[0] == key[0] and not pending.future.done():
                    pending.future.set_exception(
                        ValueError("placement group bundle canceled"))
        await self._report_resources()
        await self._pump_pending()
        return True

    # ------------------------------------------------------- object directory
    def _mark_local_sealed(self, oid: ObjectID, size: int) -> None:
        self._sealed[oid] = size
        self._lost_objects.discard(oid)
        for fut in self._object_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    async def handle_object_sealed(self, payload, conn):
        oid, size = payload["object_id"], payload["size"]
        self._mark_local_sealed(oid, size)
        background(self._report_location(oid))
        return True

    async def handle_objects_sealed_batch(self, payload, conn):
        """Coalesced seal notifications (fast-lane executors batch their
        per-return reports; one frame covers a flush window)."""
        oids = []
        for oid, size in payload["objects"]:
            self._mark_local_sealed(oid, size)
            oids.append(oid)
        background(self._report_locations(oids))
        return True

    async def _report_locations(self, oids: List[ObjectID]):
        try:
            await self.gcs.call("add_object_locations", {
                "object_ids": oids, "node_id": self.node_id})
        except Exception:
            pass

    async def _report_location(self, oid: ObjectID):
        try:
            await self.gcs.call("add_object_location", {
                "object_id": oid, "node_id": self.node_id})
        except Exception:
            pass

    async def _drop_location(self, oid: ObjectID):
        try:
            await self.gcs.call("remove_object_location", {
                "object_id": oid, "node_id": self.node_id})
        except Exception:
            pass

    def _on_object_event(self, payload):
        if payload.get("event") != "lost":
            return
        oid = payload["object_id"]
        if self.store.contains(oid):
            return  # we hold a copy; not lost here
        self._lost_objects.add(oid)
        for fut in self._object_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(False)  # False = lost

    # ------------------------------------------------ inter-node object pull
    async def _peer_client(self, address: str) -> RpcClient:
        client = self._peer_clients.get(address)
        if client is None or client.closed:
            client = RpcClient(address)
            await client.connect(timeout=10)
            self._peer_clients[address] = client
        return client

    def _start_pull(self, oid: ObjectID, prio: int = 1) -> None:
        """Idempotently request a pull of oid to the local store through
        the admission-controlled PullManager (ref: pull_manager.h:57 —
        byte budget + priority classes; retries while waiters exist)."""
        self.pulls.request(oid, prio, size_hint=self._sealed.get(oid, 0))

    async def _pull(self, oid: ObjectID) -> Optional[int]:
        backoff = 0.02
        denials = 0
        while True:
            if self.store.contains(oid) or oid in self._lost_objects:
                return self._sealed.get(oid, 0)
            if oid not in self._object_waiters:
                return None  # nobody waiting anymore
            try:
                locs = await self.gcs.call(
                    "get_object_locations", {"object_ids": [oid]})
            except Exception:
                locs = {oid: []}
            transfer_map = locs.get("__transfer__", {})
            candidates = [loc for loc in locs.get(oid, [])
                          if loc[0] != self.node_id]
            # broadcast tree: spread pullers over ALL current holders
            # instead of piling onto the list head (each completed pull
            # registers a new location, so the source set grows as the
            # broadcast progresses — ref: push_manager.h:32)
            random.shuffle(candidates)
            denied = False
            for loc in candidates:
                node_id, address = loc[0], loc[1]
                xfer_address = transfer_map.get(node_id.hex(), "")
                token = await self._acquire_transfer_token(oid, address)
                if token is False:
                    denied = True   # holder at sender cap: try another
                    continue
                try:
                    size = await self._fetch_via(oid, address, xfer_address)
                    if size is not None:
                        self._sealed[oid] = size
                        self._mark_local_sealed(oid, size)
                        self._pull_sources[oid] = node_id
                        # bounded observability maps (free also prunes)
                        for book in (self._pull_sources,
                                     self._transfer_token_high):
                            while len(book) > 4096:
                                book.pop(next(iter(book)))
                        background(self._report_location(oid))
                        return size
                    # holder no longer has it: drop the stale location
                    await self.gcs.call("remove_object_location", {
                        "object_id": oid, "node_id": node_id})
                except Exception:
                    continue
                finally:
                    if token:
                        background(self._release_transfer_token(
                            oid, address))
            if denied:
                # every holder is saturated: a fresh copy registers soon
                # — re-poll faster than the cold backoff, but with
                # jittered exponential growth so a 50-node broadcast's
                # denied majority doesn't hammer the GCS/holders at a
                # synchronized 20 Hz for the whole transfer
                denials += 1
                wait = min(0.25, 0.05 * (2 ** min(denials, 4)))
                await asyncio.sleep(wait * (0.5 + random.random()))
                continue
            denials = 0
            await asyncio.sleep(backoff)
            # cap grows to 2s: pending-local objects (task still running
            # here) shouldn't hammer the GCS with location polls
            backoff = min(2.0, backoff * 2)

    async def _acquire_transfer_token(self, oid: ObjectID, address: str):
        """Ask a holder for a sender slot. True = granted, False =
        holder saturated, None = holder predates tokens / unreachable
        (proceed ungated — the pull itself will fail if the holder is
        really gone)."""
        if self.cfg.object_transfer_max_senders_per_object <= 0:
            return None
        try:
            client = await self._peer_client(address)
            ok = await client.call("transfer_token", {
                "object_id": oid, "node_id": self.node_id.hex(),
            }, timeout=5)
        except Exception:
            return None
        return bool(ok)

    async def _release_transfer_token(self, oid: ObjectID, address: str):
        try:
            client = await self._peer_client(address)
            await client.call("transfer_token_release", {
                "object_id": oid, "node_id": self.node_id.hex(),
            }, timeout=5)
        except Exception:
            pass

    # sender-slot grants per local object: {oid: {puller_hex: expiry}}
    _TRANSFER_TOKEN_TTL_S = 120.0

    async def handle_transfer_token(self, payload, conn):
        cap = self.cfg.object_transfer_max_senders_per_object
        if cap <= 0:
            return True
        oid = payload["object_id"]
        puller = payload["node_id"]
        now = time.monotonic()
        if len(self._transfer_tokens) > 4096:
            # sweep grants of crashed pullers across ALL objects (the
            # per-oid sweep below only fires on a repeat acquire)
            for stale_oid in [o for o, g in self._transfer_tokens.items()
                              if all(exp < now for exp in g.values())]:
                del self._transfer_tokens[stale_oid]
        grants = self._transfer_tokens.setdefault(oid, {})
        for stale in [p for p, exp in grants.items() if exp < now]:
            del grants[stale]
        if puller in grants or len(grants) < cap:
            grants[puller] = now + self._TRANSFER_TOKEN_TTL_S
            high = self._transfer_token_high.get(oid, 0)
            self._transfer_token_high[oid] = max(high, len(grants))
            self._track_token_conn(conn, oid, puller)
            return True
        return False

    def _track_token_conn(self, conn, oid: ObjectID, puller: str) -> None:
        """Tie a sender-slot grant to the puller's control connection:
        when the connection closes (crash, shutdown) the grant is
        released immediately instead of pinning one of the default 2
        slots until the 120 s TTL sweep."""
        if conn is None or not hasattr(conn, "closed"):
            return
        self._token_conn_grants.setdefault(conn, set()).add((oid, puller))
        if conn not in self._token_conn_watchers:
            self._token_conn_watchers[conn] = asyncio.ensure_future(
                self._watch_token_conn(conn))

    async def _watch_token_conn(self, conn) -> None:
        try:
            await conn.closed.wait()
        except asyncio.CancelledError:
            raise  # watcher cancelled at teardown: keep the task CANCELLED
        for oid, puller in self._token_conn_grants.pop(conn, ()):
            grants = self._transfer_tokens.get(oid)
            if grants is not None:
                grants.pop(puller, None)
                if not grants:
                    self._transfer_tokens.pop(oid, None)
        self._token_conn_watchers.pop(conn, None)

    def _on_transfer_puller_gone(self, oid: ObjectID, puller: str) -> None:
        """Data-plane conn-close hook (TransferServer on_puller_gone):
        the puller's last transfer connection for `oid` closed, so its
        sender-slot grant is over — whether the transfer finished or the
        puller crashed. Releasing here means a crashed puller (whose
        release RPC never arrives) frees the slot immediately instead of
        pinning it for the 120 s TTL."""
        grants = self._transfer_tokens.get(oid)
        if grants is not None:
            grants.pop(puller, None)
            if not grants:
                self._transfer_tokens.pop(oid, None)

    async def handle_transfer_token_release(self, payload, conn):
        grants = self._transfer_tokens.get(payload["object_id"])
        if grants is not None:
            grants.pop(payload["node_id"], None)
            if not grants:
                self._transfer_tokens.pop(payload["object_id"], None)
        tracked = self._token_conn_grants.get(conn)
        if tracked is not None:
            tracked.discard((payload["object_id"], payload["node_id"]))
        return True

    async def _fetch_via(self, oid: ObjectID, address: str,
                         xfer_address: str) -> Optional[int]:
        """Pull one object from one holder: parallel raw-frame streams on
        the transfer plane when the holder advertises one, control-RPC
        chunks otherwise. A transfer-plane transport failure retries once
        through the RPC path before the holder is given up on — a dropped
        stream must not fail the pull while the holder is still alive
        (chaos: tests/test_chaos.py transfer-drop)."""
        if xfer_address:
            from .object_transfer import fetch_object

            if self.store.contains(oid):
                return self._sealed.get(oid, 0)
            holder = {}

            def _create(size: int):
                buf, entry = self.store.create_streaming(oid, size)
                holder["entry"] = entry
                # cut-through relay: advertise this IN-PROGRESS copy in
                # the directory now — downstream pullers stream behind
                # our watermark instead of waiting for our seal, so a
                # broadcast tree pipelines across its depth (retracted
                # below if the pull dies)
                background(self._report_location(oid))
                return buf

            try:
                return await fetch_object(
                    xfer_address, oid, _create,
                    streams=self.cfg.object_transfer_streams,
                    chunk_bytes=self.cfg.object_transfer_chunk_bytes,
                    seal=lambda: self.store.seal(oid),
                    abort=lambda: self.store.abort(oid),
                    admit_bytes=lambda n: self.pulls.acquire_bytes(oid, n),
                    on_progress=lambda wm: holder["entry"].advance(wm),
                    puller=self.node_id.hex())
            except Exception:
                if "entry" in holder:
                    # the early advertisement is stale — retract it
                    # BEFORE the RPC fallback can re-add it on success
                    await self._drop_location(oid)
                pass  # plane unreachable/dropped: fall through to RPC
            finally:
                self.pulls.release_bytes(oid)
        if await self._fetch_from(oid, address):
            return self._sealed.get(oid, 0)
        return None

    async def _fetch_from(self, oid: ObjectID, address: str) -> bool:
        """Chunked fetch of a sealed object from a peer raylet into the local
        store. Returns False if the peer no longer holds the object."""
        client = await self._peer_client(address)
        chunk = self.cfg.object_transfer_chunk_bytes
        first = await client.call("pull_object", {
            "object_id": oid, "offset": 0, "length": chunk}, timeout=60)
        if first is None:
            return False
        size = first["size"]
        if self.store.contains(oid):
            return True
        buf = self.store.create(oid, size)
        try:
            data = first["data"]
            buf[: len(data)] = data
            offset = len(data)
            while offset < size:
                part = await client.call("pull_object", {
                    "object_id": oid, "offset": offset, "length": chunk}, timeout=60)
                if part is None:
                    raise ConnectionError("holder dropped object mid-transfer")
                pdata = part["data"]
                buf[offset: offset + len(pdata)] = pdata
                offset += len(pdata)
        except BaseException:
            self.store.abort(oid)
            raise
        self.store.seal(oid)
        self._sealed[oid] = size
        return True

    async def handle_forget_lost(self, payload, conn):
        """Clear lost markers so a recovery attempt (lineage reconstruction
        re-creating the object elsewhere) can be awaited afresh; without this
        the lost flag is sticky and recovery could never be observed."""
        for oid in payload["object_ids"]:
            self._lost_objects.discard(oid)
        return True

    async def handle_pull_object(self, payload, conn):
        """Serve one chunk of a sealed local object to a peer raylet
        (ref: push_manager.h:32 — chunked sends on the control transport).
        An object still being received/restored here serves from behind
        its watermark (bounded wait), so the RPC fallback path cuts
        through in-progress creations the same way the transfer plane
        does."""
        oid = payload["object_id"]
        offset, length = payload["offset"], payload["length"]
        view = self.store.get(oid)
        if view is None:
            entry = self.store.inprogress(oid)
            if entry is not None:
                total = entry.size
                off = min(offset, total)
                ln = min(length, total - off)
                if not ln or await entry.wait_for(off + ln, 30.0):
                    return {"size": total,
                            "data": bytes(entry.buf[off:off + ln])}
            return None
        return {"size": len(view), "data": bytes(view[offset: offset + length])}

    async def handle_wait_objects(self, payload, conn):
        """Block until `num_returns` of `object_ids` are sealed locally, an
        object is declared lost cluster-wide, or timeout (ref: wait_manager.h).
        Missing objects trigger background pulls from remote holders."""
        oids: List[ObjectID] = payload["object_ids"]
        num_returns = payload.get("num_returns", len(oids))
        timeout = payload.get("timeout")
        # the store is authoritative: a directory entry whose file was evicted
        # must not be reported ready (get would ObjectLostError)
        ready, lost = [], []
        for oid in oids:
            if self.store.contains(oid):
                self._sealed.setdefault(oid, 0)
                ready.append(oid)
            elif oid in self._lost_objects:
                lost.append(oid)
            else:
                self._sealed.pop(oid, None)
        if len(ready) >= num_returns or len(ready) + len(lost) >= len(oids):
            return {"ready": ready, "lost": lost}
        futures = {}
        prio = payload.get("prio", 1)  # 0 = a worker is blocked on args
        for oid in oids:
            if oid not in self._sealed and oid not in self._lost_objects:
                fut = asyncio.get_event_loop().create_future()
                self._object_waiters.setdefault(oid, []).append(fut)
                futures[oid] = fut
                self._start_pull(oid, prio)
        deadline = None if timeout is None else asyncio.get_event_loop().time() + timeout
        while len(ready) < num_returns and len(ready) + len(lost) < len(oids):
            remaining = None if deadline is None else max(0.0, deadline - asyncio.get_event_loop().time())
            pending = [f for f in futures.values() if not f.done()]
            if not pending:
                break
            # bound each wait so we also poll the local store (seal paths that
            # bypass this raylet's directory, e.g. a co-located process)
            poll = 0.05 if remaining is None else min(0.05, remaining)
            done, _ = await asyncio.wait(pending, timeout=poll,
                                         return_when=asyncio.FIRST_COMPLETED)
            for oid, fut in futures.items():
                if not fut.done() and oid not in self._sealed and self.store.contains(oid):
                    self._sealed.setdefault(oid, 0)
                    fut.set_result(True)
            ready = [oid for oid in oids if oid in self._sealed]
            lost = [oid for oid in oids if oid in self._lost_objects and oid not in self._sealed]
            if not done and remaining is not None and remaining <= poll \
                    and len(ready) < num_returns:
                break  # timeout
        for oid, fut in futures.items():
            if not fut.done():
                try:
                    self._object_waiters.get(oid, []).remove(fut)
                except ValueError:
                    pass
                fut.cancel()
            if oid in self._object_waiters and not self._object_waiters[oid]:
                del self._object_waiters[oid]
        return {"ready": ready, "lost": lost}

    async def handle_free_objects(self, payload, conn):
        for oid in payload["object_ids"]:
            if self._sealed.pop(oid, None) is not None or self.store.contains(oid):
                background(self._drop_location(oid))
            self.store.delete(oid)
            self._transfer_tokens.pop(oid, None)
            self._transfer_token_high.pop(oid, None)
            self._pull_sources.pop(oid, None)
        return True

    async def handle_pin_objects(self, payload, conn):
        for oid in payload["object_ids"]:
            self.store.pin(oid)
        return True

    async def handle_unpin_objects(self, payload, conn):
        for oid in payload["object_ids"]:
            self.store.unpin(oid)
        return True

    # ------------------------------------------------------------ state api
    async def handle_node_stats(self, payload, conn):
        return {
            "node_id": self.node_id,
            "resources_total": self.resources.total.to_dict(),
            "resources_available": self.resources.available.to_dict(),
            "num_workers": len(self._workers),
            "num_idle_workers": len(self._idle),
            "num_leases": len(self._leases),
            "num_pending_leases": len(self._pending_leases),
            "num_objects": len(self._sealed),
            "store_used_bytes": self.store.used_bytes(),
            "store_capacity_bytes": self.store.capacity,
            # per-lease detail: who holds this node's resources (the
            # `ray memory`-style leak-hunting view)
            "leases": [{
                "lease_id": lease.lease_id,
                "resources": lease.resources.to_dict(),
                "owner": lease.owner_address,
                "lane": lease.lane,
                "actor_id": (lease.worker.actor_id.hex()
                             if lease.worker.actor_id else None),
            } for lease in self._leases.values()],
        }
