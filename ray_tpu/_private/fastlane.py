"""Fast-lane task plane: native shm rings between an owner and its
leased workers (binding over native/fastlane.cc).

The reference's steady-state submission path is a direct worker->worker
gRPC PushTask once a lease is held (ref: transport/normal_task_submitter.h:227,
:58-60 SchedulingKey lease pool). Here the steady state drops sockets
entirely: task frames ride a shared-memory ring pair per (owner, worker)
— push from the submitting user thread (no event loop on the hot path),
pop on a dedicated worker thread, replies matched by sequence number on
a driver-side reply thread. The asyncio RPC plane still owns leasing,
placement, failure handling, cancellation, streaming and anything cold;
eligibility for the lane is checked per task and everything else falls
back transparently.

Parallelism: a LanePool grows to ``fastlane_width`` lanes (one leased
worker each) while backlog exists, balances by least-outstanding, and
releases idle lanes back to the raylet, mirroring the reference's lease
pool dynamics. Per-lane in-flight is capped (``fastlane_window``) so a
burst of slow tasks spreads over workers instead of convoying behind
one.

Actor calls: one lane per actor handle-owner pair. Ordering: once the
lane attaches, ALL calls from this owner ride it (ring FIFO == submit
order); during attach, calls buffer locally and flush in order.
"""

from __future__ import annotations

import asyncio
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import locking
from .ids import ObjectID
from .task_spec import ArgKind, TaskSpec
from .. import exceptions as exc


def _spec_deps(spec: TaskSpec) -> List[ObjectID]:
    """The ObjectRef args _pack_args pinned for this spec — lane
    completion paths must unpin exactly these (inlined VALUE args were
    never pinned)."""
    return [a.object_id for a in spec.args if a.kind == ArgKind.OBJECT_REF]

# Ring capacity per direction. Shm files are cheap; generous headroom
# means even many-arg specs (inline VALUE args are individually capped
# at the small-object threshold by _pack_args) batch into one frame.
_RING_CAP = 8 << 20


def _finalize_lane_task(core, spec: TaskSpec, event: threading.Event,
                        err: BaseException) -> None:
    """Single copy of the lane-task terminal teardown: error stored
    BEFORE the event fires, inflight/lane-event/dep bookkeeping cleaned,
    terminal task event recorded (shared by _fail_pending, the feeder's
    cancelled-drop path, and queue-side cancellation)."""
    core._store_error(spec, err)
    core._record_task_event(spec.task_id, state="FAILED",
                            end_time=time.time(), error=str(err))
    core._inflight.pop(spec.task_id, None)
    for oid in spec.return_ids():
        core._lane_events.pop(oid, None)
    for oid in _spec_deps(spec):
        core._unpin_task_dep(oid)
    event.set()


def lanes_enabled() -> bool:
    if os.environ.get("RAY_TPU_FASTLANE", "1") == "0":
        return False
    try:
        from .._native import get_lib

        return get_lib() is not None
    except Exception:
        return False


class _Lane:
    """One attached (owner -> leased worker) ring pair."""

    def __init__(self, core, grant: dict, sub, rep, client):
        self.core = core
        self.grant = grant
        self.worker_address = grant["worker_address"]
        self.sub = sub          # owner pushes task frames
        self.rep = rep          # worker pushes reply frames
        self.client = client    # asyncio client (liveness + cancel path)
        self.pending: Dict[int, Tuple[TaskSpec, threading.Event]] = {}
        self.outstanding = 0
        self.last_used = time.monotonic()
        self.dead = False
        self.on_slot: Optional[Callable[[], None]] = None  # pool wakeup
        self._seq = 0
        self._lock = locking.make_lock("_Lane._lock")
        # serializes EVERY cross-thread ring touch against teardown:
        # free() (munmap) must never run under a concurrent push OR
        # close_write — rtpu_ring_close on a freed mapping segfaults
        # (observed: reclaim-path close() racing the reply thread's
        # _cleanup_rings)
        self._push_lock = locking.make_lock("_Lane._push_lock")
        self._sub_freed = False
        self._rep_freed = False
        self._reply_thread = threading.Thread(
            target=self._reply_loop, daemon=True,
            name=f"lane_reply_{self.worker_address[-8:]}")
        self._reply_thread.start()

    # ---- submit path (called from user threads / the pool feeder) ----
    def submit(self, spec: TaskSpec, event: threading.Event) -> bool:
        return self.submit_many([(spec, event)]) == 1

    def submit_many(self, items: List[Tuple[TaskSpec, threading.Event]]) -> int:
        """Ship a chunk of tasks as ONE frame (one pickle, one ring
        push) — burst submission amortizes the per-frame cost. Returns
        how many were accepted (0 on a dead lane; never partial)."""
        if not items:
            return 0
        with self._lock:
            if self.dead:
                return 0
            batch = []
            for spec, event in items:
                self._seq += 1
                self.pending[self._seq] = (spec, event)
                batch.append((self._seq, spec))
            self.outstanding += len(items)
            self.last_used = time.monotonic()
        for _, spec in batch:
            info = self.core._inflight.get(spec.task_id)
            if info is not None:
                info["worker_address"] = self.worker_address
        timed = self.core.cfg.submit_stage_timers_enabled
        t_frame = time.perf_counter() if timed else 0.0
        frame = pickle.dumps(batch, protocol=5)
        try:
            with self._push_lock:
                if self._sub_freed:
                    raise BrokenPipeError("lane torn down")
                if not self.sub.push(frame, timeout_ms=2000):
                    raise BrokenPipeError("ring full")
        except ValueError:
            # frame larger than the ring: the lane is perfectly healthy,
            # this batch just can't ride it — un-register and let the
            # caller route it elsewhere (killing the lane here would
            # requeue the same chunk into a grow/kill spin)
            with self._lock:
                for seq, _ in batch:
                    self.pending.pop(seq, None)
                self.outstanding -= len(batch)
            return -1
        except BrokenPipeError:
            with self._lock:
                for seq, _ in batch:
                    self.pending.pop(seq, None)
                self.outstanding -= len(batch)
            self._mark_dead()
            return 0
        if timed:
            from .core_worker import _stage_hist  # lazy: import cycle

            hist = _stage_hist()
            now = time.perf_counter()
            # per-frame cost (one pickle + one ring push per batch)
            hist.observe(now - t_frame, tags={"stage": "lane_push"})
            for _, event in items:
                enq = getattr(event, "_lane_enq_t", None)
                if enq is not None:
                    hist.observe(now - enq, tags={"stage": "lane_queue"})
        return len(batch)

    # ---- reply path ----
    def _reply_loop(self):
        while True:
            try:
                frame = self.rep.pop(timeout_ms=200)
            except (BrokenPipeError, ValueError):
                break
            if frame is None:
                if self.dead or self.client.closed:
                    break
                continue
            try:
                seq, reply = pickle.loads(frame)
            except Exception:
                # An undecodable reply means the ring is corrupt or the
                # worker wrote garbage — its pending[seq] entry can never
                # be matched, so skipping would leak the window slot and
                # block the submitter's get() for the lane's lifetime.
                # Treat it as lane-fatal: _fail_pending below resubmits
                # or errors every outstanding task.
                break
            with self._lock:
                entry = self.pending.pop(seq, None)
                if entry is not None:
                    self.outstanding -= 1
            if entry is None:
                continue
            spec, event = entry
            try:
                errored = self.core._handle_task_reply(spec, reply)
                terminal = "FAILED" if errored else "FINISHED"
                self.core._record_transition(
                    spec.task_id, terminal, state=terminal,
                    end_time=time.time(),
                    error="application error" if errored else None)
            finally:
                self.core._inflight.pop(spec.task_id, None)
                for oid in spec.return_ids():
                    self.core._lane_events.pop(oid, None)
                for oid in _spec_deps(spec):
                    self.core._unpin_task_dep(oid)
                event.set()
                if self.on_slot is not None:
                    self.on_slot()
        self._mark_dead()
        self._fail_pending()
        if self.on_slot is not None:
            self.on_slot()
        self._cleanup_rings()

    def _cleanup_rings(self):
        """Reply-thread exit owns teardown: unmap both rings and unlink
        their files (16 MB of tmpfs per lane otherwise leaks on every
        attach/release cycle). The push lock keeps every other thread
        (submitters pushing, close()/`_mark_dead` writing close flags)
        out of both mappings while they die."""
        with self._push_lock:
            self._rep_freed = True
            try:
                self.rep.free()
            except Exception:
                pass
            self._sub_freed = True
            try:
                self.sub.free()
            except Exception:
                pass
        for ring in (self.sub, self.rep):
            try:
                ring.unlink()
            except Exception:
                pass

    def _mark_dead(self):
        with self._lock:
            if self.dead:
                return
            self.dead = True
        with self._push_lock:
            if not self._sub_freed:
                try:
                    self.sub.close_write()
                except Exception:
                    pass

    def _fail_pending(self):
        """Worker died: resubmit retriable pending tasks through the
        asyncio path, error the rest (ref: lease failure handling in
        normal_task_submitter)."""
        with self._lock:
            entries = list(self.pending.values())
            self.pending.clear()
            self.outstanding = 0
        for spec, event in entries:
            if spec.max_retries > 0 and not spec.is_actor_task():
                spec.max_retries -= 1

                async def _resub(spec=spec, event=event):
                    try:
                        # deps transfer to the asyncio path, whose
                        # finally unpins them
                        await self.core._submit_normal(spec,
                                                       _spec_deps(spec))
                    finally:
                        for oid in spec.return_ids():
                            self.core._lane_events.pop(oid, None)
                        event.set()

                self.core.io.spawn(_resub())
            elif spec.is_actor_task() and spec.max_retries > 0:
                # retriable actor call: ride the restart/retry path (may
                # re-execute — at-least-once, like the reference's
                # max_task_retries)
                spec.max_retries -= 1

                async def _resub_actor(spec=spec, event=event):
                    try:
                        await self.core._submit_actor_task(
                            spec, _spec_deps(spec))
                    finally:
                        for oid in spec.return_ids():
                            self.core._lane_events.pop(oid, None)
                        event.set()

                self.core.io.spawn(_resub_actor())
            else:
                err: BaseException
                info = self.core._inflight.get(spec.task_id)
                if info is not None and info.get("canceled"):
                    # a force-cancel killed the worker: surface the
                    # cancellation, not the crash it caused
                    err = exc.TaskCancelledError(
                        f"task {spec.function.repr_name} was cancelled")
                elif spec.is_actor_task():
                    err = exc.ActorDiedError(
                        spec.actor_id,
                        "the actor died while this call was in flight "
                        "(set max_task_retries to retry on restart)")
                else:
                    err = exc.WorkerCrashedError(
                        f"fast-lane worker {self.worker_address} died")
                _finalize_lane_task(self.core, spec, event, err)

    def close(self, *, release_lease: bool = True):
        self._mark_dead()
        with self._push_lock:
            if not self._rep_freed:
                try:
                    self.rep.close_write()
                except Exception:
                    pass
        # reap the reply thread (it exits on dead-flag + ring close
        # within one 200ms pop timeout); the reply loop itself calls
        # close() on lane-fatal errors, so never self-join
        if threading.current_thread() is not self._reply_thread:
            self._reply_thread.join(timeout=2.0)
        if release_lease and not self.client.closed:
            async def _ret():
                try:
                    await self.grant["_raylet"].call("return_worker", {
                        "lease_id": self.grant["lease_id"],
                        "disconnect_worker": False,
                    })
                except Exception:
                    pass

            self.core.io.spawn(_ret())


class LanePool:
    """Pool of task lanes with a driver-side feeder queue.

    ``try_submit`` only enqueues (user threads never block); a feeder
    thread drains the queue onto the least-loaded live lane, growing the
    pool (one leased worker per lane, up to ``width``) while a backlog
    exists — the same dynamics as the reference's per-SchedulingKey
    lease pool, with the ring as the per-worker pipeline. Per-lane
    in-flight is capped at ``window`` so slow tasks spread across
    workers instead of convoying."""

    def __init__(self, core, width: int, window: int):
        self.core = core
        self.width = width
        self.window = window
        self.lanes: List[_Lane] = []
        self._growing = False
        self._grow_fail_until = 0.0
        self._lock = locking.make_lock("LanePool._lock")
        self.closed = False
        self._queue: List[Tuple[TaskSpec, threading.Event]] = []
        self._qlock = locking.make_lock("LanePool._qlock")
        self._qevent = threading.Event()
        self._slot = threading.Event()
        self._feeder = threading.Thread(target=self._feed_loop, daemon=True,
                                        name="lane_feeder")
        self._feeder.start()

    # -- user-thread side --
    def try_submit(self, spec: TaskSpec, event: threading.Event) -> bool:
        if self.closed:
            return False
        if self.core.cfg.submit_stage_timers_enabled:
            # feeder-queue wait stamp, read by _Lane.submit_many (rides
            # the event object so the queue tuple shape stays unchanged
            # through the requeue/cancel paths)
            event._lane_enq_t = time.perf_counter()
        with self._qlock:
            self._queue.append((spec, event))
        self._qevent.set()
        return True

    def _signal_slot(self):
        self._slot.set()

    # -- feeder --
    def _feed_loop(self):
        while not self.closed:
            if not self._qevent.wait(timeout=0.2):
                continue
            self._pump()
        # drain on close: surface shutdown errors so getters unblock
        with self._qlock:
            rest, self._queue = self._queue, []
        for spec, event in rest:
            try:
                self.core._store_error(spec, exc.WorkerCrashedError(
                    "shutdown while task queued on fast lane"))
            except Exception:
                pass
            event.set()

    _CHUNK = 16

    def _pump(self) -> None:
        while not self.closed:
            with self._qlock:
                if not self._queue:
                    self._qevent.clear()
                    return
            with self._lock:
                live = [l for l in self.lanes if not l.dead]
                self.lanes = live
                best = min(live, key=lambda l: l.outstanding) if live else None
                backlogged = best is None or best.outstanding >= 1
                can_grow = (len(live) < self.width and not self._growing
                            and time.monotonic() > self._grow_fail_until)
                if backlogged and can_grow:
                    self._growing = True
                    self.core.io.spawn(self._grow())
            if best is None:
                if self._growing:
                    self._slot.wait(timeout=0.05)
                    self._slot.clear()
                    continue
                # cannot attach any lane: asyncio fallback keeps liveness
                with self._qlock:
                    if not self._queue:
                        continue
                    spec, event = self._queue.pop(0)
                self._fallback(spec, event)
                continue
            room = self.window - best.outstanding
            if room <= 0:
                self._slot.wait(timeout=0.05)
                self._slot.clear()
                continue
            with self._qlock:
                take = min(room, self._CHUNK, len(self._queue))
                chunk = self._queue[:take]
                del self._queue[:take]
            if not chunk:
                continue
            # a task cancelled while queued here must NOT dispatch — at
            # cold start the cancel can land before any lane (or even
            # lease) exists, and nothing downstream would re-check
            # (observed: force-cancelled 60s sleeper running to
            # completion, its get() timing out)
            live_chunk = []
            for spec, event in chunk:
                info = self.core._inflight.get(spec.task_id)
                if info is not None and info.get("canceled"):
                    _finalize_lane_task(
                        self.core, spec, event, exc.TaskCancelledError(
                            f"task {spec.function.repr_name} "
                            f"was cancelled"))
                else:
                    live_chunk.append((spec, event))
            chunk = live_chunk
            if not chunk:
                continue
            rc = best.submit_many(chunk)
            if rc == 0:  # lane died mid-flight: requeue for another lane
                with self._qlock:
                    self._queue[:0] = chunk
            elif rc == -1:  # chunk too large for the ring: shrink
                if len(chunk) > 1:
                    with self._qlock:
                        self._queue[:0] = chunk[1:]
                    chunk = chunk[:1]
                if len(chunk) == 1 and best.submit_many(chunk) < 1:
                    # a single spec that outsizes the ring: asyncio path
                    self._fallback(*chunk[0])

    def cancel_queued(self, task_id) -> bool:
        """Remove a not-yet-dispatched task from the feeder queue and
        fail it as cancelled IMMEDIATELY — without this, a queued task's
        cancellation only lands at the next dispatch attempt, which can
        be a full task-runtime away when the lane window is occupied."""
        with self._qlock:
            hit = None
            for i, (spec, event) in enumerate(self._queue):
                if spec.task_id == task_id:
                    hit = (spec, event)
                    del self._queue[i]
                    break
        if hit is None:
            return False
        _finalize_lane_task(self.core, hit[0], hit[1],
                            exc.TaskCancelledError(
                                f"task {hit[0].function.repr_name} "
                                f"was cancelled"))
        return True

    def cancel_pending(self, task_id) -> bool:
        """Fail a lane task that already DISPATCHED into a ring but may
        sit behind long tasks on the lane's serial worker. The owner
        finalizes promptly; the worker (told separately via cancel_task)
        skips or interrupts the execution, and its eventual reply for
        the forgotten seq is dropped by the reply loop."""
        for lane in list(self.lanes):
            with lane._lock:
                hit_seq = None
                for seq, (spec, event) in lane.pending.items():
                    if spec.task_id == task_id:
                        hit_seq = seq
                        break
                if hit_seq is None:
                    continue
                spec, event = lane.pending.pop(hit_seq)
                lane.outstanding -= 1
            _finalize_lane_task(self.core, spec, event,
                                exc.TaskCancelledError(
                                    f"task {spec.function.repr_name} "
                                    f"was cancelled"))
            if lane.on_slot is not None:
                lane.on_slot()
            return True
        return False

    def _fallback(self, spec: TaskSpec, event: threading.Event):
        async def _run(spec=spec, event=event):
            try:
                await self.core._submit_normal(spec, _spec_deps(spec))
            finally:
                for oid in spec.return_ids():
                    self.core._lane_events.pop(oid, None)
                event.set()

        self.core.io.spawn(_run())

    async def _grow(self):
        try:
            lane = await attach_task_lane(self.core)
            with self._lock:
                if lane is None:
                    # back off so a broken environment doesn't lease-storm
                    self._grow_fail_until = time.monotonic() + 2.0
                elif self.closed:
                    lane.close()
                else:
                    lane.on_slot = self._signal_slot
                    self.lanes.append(lane)
        finally:
            with self._lock:
                self._growing = False
            self._slot.set()

    def maintain(self, idle_timeout: float = 8.0):
        """Release EVERY lane idle beyond the timeout. No warm lane is
        kept: a held lease is capacity the rest of the cluster (queued
        leases, placement-group reservations) cannot see, and
        re-attaching after an idle gap costs one lease round trip."""
        now = time.monotonic()
        with self._lock:
            keep, drop = [], []
            for lane in self.lanes:
                if lane.dead:
                    drop.append((lane, False))
                elif (lane.outstanding == 0
                        and now - lane.last_used > idle_timeout):
                    drop.append((lane, True))
                else:
                    keep.append(lane)
            self.lanes = keep
        for lane, release in drop:
            lane.close(release_lease=release)

    def reclaim(self, lease_id: int) -> bool:
        """Raylet-driven preemption: release the lane holding this lease
        if it is idle (pending demand — queued leases or PG bundle
        reservations — outranks a warm idle lane; ref: the reference's
        idle-worker return path in worker_pool.h)."""
        with self._lock:
            target = None
            for lane in self.lanes:
                if lane.grant.get("lease_id") == lease_id:
                    target = lane
                    break
            if target is None or target.outstanding > 0:
                return False
            self.lanes.remove(target)
        target.close(release_lease=True)
        return True

    def close(self):
        with self._lock:
            self.closed = True
            lanes, self.lanes = self.lanes, []
        self._qevent.set()  # wake the feeder so it drains and exits
        if threading.current_thread() is not self._feeder:
            self._feeder.join(timeout=2.0)
        for lane in lanes:
            lane.close(release_lease=False)


async def _make_rings(core, tag: str):
    """Create the ring pair in the node's shm store dir."""
    from .._native import Ring

    base = os.path.join(core.store.dir, f"lane_{tag}")
    sub = Ring(base + ".sub", _RING_CAP, create=True)
    rep = Ring(base + ".rep", _RING_CAP, create=True)
    return sub, rep, base


async def attach_task_lane(core) -> Optional[_Lane]:
    """Lease a worker and attach a normal-task lane to it."""
    probe = TaskSpec.lane_probe(core.job_id, core.address)
    try:
        grant = await core._request_lease(probe)
    except Exception:
        return None
    try:
        client = await core._client_for(grant["worker_address"])
        tag = f"{core.worker_id.hex()[:8]}_{os.getpid()}_{id(grant) & 0xffffff:x}"
        sub, rep, base = await _make_rings(core, tag)
        ok = await client.call("fastlane_attach", {
            "sub": base + ".sub", "rep": base + ".rep", "kind": "task",
        }, timeout=10)
        if not ok:
            raise RuntimeError("attach refused")
        return _Lane(core, grant, sub, rep, client)
    except Exception:
        try:
            await grant["_raylet"].call("return_worker", {
                "lease_id": grant["lease_id"], "disconnect_worker": False})
        except Exception:
            pass
        return None


class ActorLane:
    """Per-actor fast lane. All calls from this owner ride it once
    attached (ordering = ring FIFO = submission order). Calls buffer in
    a local list and a single flusher thread drains them with
    ``submit_many`` — burst call patterns coalesce into batched frames
    (one pickle + one ring push per chunk), and the attach window is
    just the flusher not having started yet."""

    _CHUNK = 32

    def __init__(self, core, actor_id):
        self.core = core
        self.actor_id = actor_id
        self.lane: Optional[_Lane] = None
        self.state = "attaching"  # attaching | up | down
        self._buffer: List[Tuple[TaskSpec, threading.Event]] = []
        self._lock = locking.make_lock("ActorLane._lock")
        self._flush_event = threading.Event()
        core.io.spawn(self._attach())

    def submit(self, spec: TaskSpec, event: threading.Event) -> bool:
        """False → caller must use the asyncio path."""
        with self._lock:
            if self.state == "down":
                return False
            self._buffer.append((spec, event))
        self._flush_event.set()
        return True

    async def _attach(self):
        try:
            state = await self.core._wait_actor_alive(self.actor_id)
            client = await self.core._client_for(state.address)
            tag = (f"a{self.actor_id.hex()[:8]}_"
                   f"{self.core.worker_id.hex()[:8]}_{os.getpid()}")
            sub, rep, base = await _make_rings(self.core, tag)
            ok = await client.call("fastlane_attach", {
                "sub": base + ".sub", "rep": base + ".rep", "kind": "actor",
            }, timeout=10)
            if not ok:
                raise RuntimeError("attach refused")
            grant = {"worker_address": state.address, "lease_id": -1,
                     "_raylet": self.core.raylet}
            lane = _Lane(self.core, grant, sub, rep, client)
        except Exception:
            lane = None
        if lane is None:
            self._drain_down()
            return
        with self._lock:
            self.lane = lane
            self.state = "up"
        threading.Thread(target=self._flush_loop, daemon=True,
                         name=f"actor_lane_{self.actor_id.hex()[:8]}").start()

    def _flush_loop(self):
        while True:
            if not self._flush_event.wait(timeout=0.5):
                with self._lock:
                    if self.state != "up":
                        return
                continue
            self._flush_event.clear()
            while True:
                with self._lock:
                    if self.state != "up":
                        return
                    chunk = self._buffer[:self._CHUNK]
                    del self._buffer[:len(chunk)]
                if not chunk:
                    break
                lane = self.lane
                rc = 0 if lane is None else lane.submit_many(chunk)
                if rc == -1:
                    # over-ring-size chunk: retry one by one; a single
                    # call that still doesn't fit takes the asyncio path
                    # (a >8MB inline spec — refs and big args were
                    # already externalized by _pack_args)
                    for item in chunk:
                        if lane.submit_many([item]) < 1:
                            self._spawn_asyncio(*item)
                    continue
                if rc == 0:
                    with self._lock:
                        self._buffer[:0] = chunk
                    self._drain_down()
                    return

    def _drain_down(self):
        """Lane gone: flush everything buffered through the asyncio
        path, preserving order, and reject future lane submissions."""
        with self._lock:
            self.state = "down"
            buffered, self._buffer = self._buffer, []
            lane, self.lane = self.lane, None
        if lane is not None:
            lane.close(release_lease=False)
        for spec, event in buffered:
            self._spawn_asyncio(spec, event)

    def _spawn_asyncio(self, spec: TaskSpec, event: threading.Event):
        async def _run(spec=spec, event=event):
            try:
                await self.core._submit_actor_task(spec, _spec_deps(spec))
            finally:
                for oid in spec.return_ids():
                    self.core._lane_events.pop(oid, None)
                event.set()

        self.core.io.spawn(_run())

    def close(self):
        self._drain_down()
