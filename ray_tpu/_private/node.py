"""Node: process/session bring-up for head and worker nodes.

TPU-native analog of the reference launcher (ref: python/ray/_private/node.py,
services.py — spawns gcs_server/raylet binaries). Here the GCS and raylet are
asyncio servers hosted on a dedicated IO thread inside the head process;
their socket-based contracts are identical whether they live in-process or as
separate daemons, which is what lets the native (C++) substrate replace them
under the same wire protocol in later milestones.
"""

from __future__ import annotations

import atexit
import os
import shutil
import time
import uuid
from typing import Dict, Optional

from .config import global_config
from .gcs import GcsServer
from .ids import NodeID
from .object_store import SharedObjectStore
from .raylet import Raylet
from .rpc import EventLoopThread

from .config import TEMP_ROOT as _TEMP_ROOT


def default_resources() -> Dict[str, float]:
    res = {"CPU": float(os.cpu_count() or 1)}
    res["memory"] = float(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))
    # TPU detection: count local TPU chips without initializing the runtime
    # for CPU-only runs (ref: _private/accelerators/tpu.py:109).
    num_tpus = _detect_tpu_chips()
    if num_tpus:
        res["TPU"] = float(num_tpus)
    return res


def _detect_tpu_chips() -> int:
    if os.environ.get("RAY_TPU_FAKE_CHIPS"):
        return int(os.environ["RAY_TPU_FAKE_CHIPS"])
    try:
        import glob

        return len(glob.glob("/dev/accel*")) or len(glob.glob("/dev/vfio/*"))
    except Exception:
        return 0


def _detect_accelerator_type() -> str:
    """TPU generation label from the VM metadata env TPU runtimes set
    (ref: accelerators/tpu.py get_current_node_accelerator_type —
    there read from instance metadata; queued-resources/GKE export it
    as TPU_ACCELERATOR_TYPE, e.g. 'v5litepod-8'). Values align with
    ray_tpu.util.accelerators constants; tasks target them via
    ``@remote(accelerator_type=...)``."""
    acc = (os.environ.get("TPU_ACCELERATOR_TYPE")
           or os.environ.get("ACCELERATOR_TYPE", ""))
    if not acc:
        return ""
    gen = acc.split("-")[0].lower()
    mapping = {"v2": "TPU-V2", "v3": "TPU-V3", "v4": "TPU-V4",
               "v5litepod": "TPU-V5LITE", "v5e": "TPU-V5LITE",
               "v5p": "TPU-V5P", "v6e": "TPU-V6E"}
    # unknown generations publish NOTHING: fabricating "TPU-NVIDIA" from
    # a GPU VM's ACCELERATOR_TYPE would pollute the label namespace
    return mapping.get(gen, "")


class Node:
    """A head (GCS + raylet) or worker (raylet only) node."""

    def __init__(
        self,
        head: bool,
        session_name: Optional[str] = None,
        gcs_address: Optional[str] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        io: Optional[EventLoopThread] = None,
        object_store_memory: Optional[int] = None,
        port: Optional[int] = None,
        node_ip: Optional[str] = None,
        external_store_address: Optional[str] = None,
    ):
        """``port``: bind the head GCS on TCP (0 = ephemeral) so worker nodes
        on other hosts can join over DCN; default is a unix socket
        (single-host). ``node_ip``: the routable IP this node advertises to
        peers (TCP binds listen on 0.0.0.0); defaults to loopback, which is
        correct for single-host test clusters only."""
        self.head = head
        cfg = global_config()
        if head:
            self.session_name = session_name or (
                f"rtpu_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}_{uuid.uuid4().hex[:6]}"
            )
        else:
            assert session_name and gcs_address, "worker nodes need a session + GCS"
            self.session_name = session_name
        self.session_dir = os.path.join(_TEMP_ROOT, self.session_name)
        os.makedirs(self.session_dir, exist_ok=True)
        self.node_id = NodeID.from_random()
        self.node_ip = node_ip or "127.0.0.1"
        if gcs_address:
            self.gcs_address = gcs_address
        elif port is not None:
            self.gcs_address = f"0.0.0.0:{port}"  # advertised via node_ip
        else:
            self.gcs_address = os.path.join(self.session_dir, "gcs.sock")
        tcp_mode = port is not None or (gcs_address and "/" not in gcs_address)
        if tcp_mode:
            self.raylet_address = "0.0.0.0:0"     # ephemeral, all interfaces
        else:
            self.raylet_address = os.path.join(
                self.session_dir, f"raylet_{self.node_id.hex()[:12]}.sock")
        self.io = io or EventLoopThread(name="ray_tpu_node")
        self._owns_io = io is None

        # Each node owns a distinct store namespace; cross-node access rides
        # the raylet pull path (a same-host shortcut would mask transfer bugs
        # in the multi-node test harness, ref: cluster_utils.py:135).
        self.store = SharedObjectStore(
            os.path.join(self.session_name, f"node_{self.node_id.hex()[:12]}"),
            object_store_memory or cfg.object_store_memory_bytes,
        )
        self.gcs_server: Optional[GcsServer] = None
        if head:
            # journal in the session dir: a restarted GCS rebuilds its
            # actor/PG/job/KV tables from it (the Redis-persistence analog)
            self.gcs_server = GcsServer(
                self.gcs_address,
                journal_path=os.path.join(self.session_dir, "gcs_journal.bin"),
                advertise_host=self.node_ip,
                # external kv_server (the Redis role): head-disk loss
                # becomes survivable — a new head re-seeds from it
                external_store_address=external_store_address)
        node_labels = dict(labels or {})
        acc_type = _detect_accelerator_type()
        if acc_type and "accelerator_type" not in node_labels:
            node_labels["accelerator_type"] = acc_type
        self.raylet = Raylet(
            node_id=self.node_id,
            session_name=self.session_name,
            socket_path=self.raylet_address,
            gcs_address=self.gcs_address,
            resources=resources or default_resources(),
            store=self.store,
            labels=node_labels,
            advertise_host=self.node_ip,
        )
        self._started = False

    def start(self):
        async def _start():
            if self.gcs_server is not None:
                await self.gcs_server.start()
                self.gcs_address = self.gcs_server.server.address
                self.raylet.gcs_address = self.gcs_address
                # remote joiners (CLI worker nodes) fetch the session
                # name through the KV instead of a side channel
                self.gcs_server.storage.put(
                    "cluster", "session_name", self.session_name.encode())
            await self.raylet.start()
            self.raylet_address = self.raylet.server.address

        self.io.run(_start(), timeout=30)
        self._started = True
        atexit.register(self.stop)

    def stop(self):
        if not self._started:
            return
        self._started = False
        try:
            async def _stop():
                await self.raylet.stop()
                if self.gcs_server is not None:
                    await self.gcs_server.stop()

            self.io.run(_stop(), timeout=10)
        except Exception:
            pass
        if self._owns_io:
            self.io.stop()
        self.store.destroy()
        if self.head:
            # whole-session cleanup: worker nodes' store namespaces too
            shutil.rmtree(os.path.join("/dev/shm", self.session_name),
                          ignore_errors=True)
            shutil.rmtree(self.session_dir, ignore_errors=True)

    def die(self):
        """Abrupt node death (fault injection): kill workers + drop
        connections; no graceful unregister, no store cleanup."""
        if not self._started:
            return
        self._started = False
        try:
            self.io.run(self.raylet.die(), timeout=10)
        except Exception:
            pass
        if self._owns_io:
            self.io.stop()
