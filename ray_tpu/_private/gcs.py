"""Global Control Service: cluster metadata + control plane.

TPU-native analog of the reference GCS (ref: src/ray/gcs/gcs_server/
gcs_server.h, gcs_actor_manager.cc:394,480,858, gcs_node_manager.h,
gcs_kv_manager.h, gcs_job_manager.h) with its pubsub (ref: src/ray/pubsub/
publisher.h:300) collapsed into push frames on the same RPC server. Storage is
pluggable like the reference store_client (ref: gcs/store_client/
store_client.h:33): in-memory by default, file-backed journal for
fault-tolerant restart (the Redis-persistence analog).

Tables: nodes, actors, jobs, KV (function blobs, named refs), placement
groups. All mutating handlers publish deltas on pubsub channels so raylets and
core workers keep eventually-consistent views (the RaySyncer role, ref:
src/ray/common/ray_syncer/ray_syncer.h:73).
"""

from __future__ import annotations

import asyncio
import json
import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from .gcs_storage import RemoteStoreClient, Storage
from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID
from .rpc import RpcServer, ServerConnection, background

# Actor lifecycle states (ref: gcs.proto ActorTableData.ActorState)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str                      # raylet socket path
    resources_total: Dict[str, float]
    resources_available: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    # TPU slice topology (ICI coordinates of this host's chips)
    slice_name: str = ""
    host_index: int = 0
    resource_seq: int = 0     # last-applied availability report sequence
    store_dir: str = ""       # shm namespace (same-host drivers attach to it)
    # resource shapes of leases queued on this raylet (the autoscaler's
    # demand signal; ref: autoscaler v2 cluster-status resource demands)
    pending_demands: list = field(default_factory=list)
    # bulk object-transfer listener (object_transfer.py); "" = peer
    # predates the transfer plane, pulls fall back to control-RPC chunks
    # (wire schema rule: appended field, decode fills the default)
    transfer_address: str = ""
    # NTP-style estimate of (GCS clock - this node's clock), seconds,
    # reported by the raylet's clock-sync loop; timestamps from this
    # node compose cluster-wide as local_ts + clock_offset
    clock_offset: float = 0.0
    # GCS wall clock of the last sign of life from this node (successful
    # health probe or resource report) — heartbeat age in `cli status` /
    # dashboard is now - last_heartbeat_t (wire schema rule: appended
    # field, decode fills the default)
    last_heartbeat_t: float = 0.0


@dataclass
class ActorInfo:
    actor_id: ActorID
    state: str
    name: str = ""
    namespace: str = ""
    detached: bool = False    # survives its creating driver (ref: detached
    #                           lifetime, gcs_actor_manager job cleanup)
    owner_is_driver: bool = True  # created by a driver (vs by another actor)
    address: str = ""                 # worker socket when ALIVE
    node_id: Optional[NodeID] = None
    class_name: str = ""
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: str = ""
    creation_spec: Any = None         # pickled TaskSpec for restarts


class GcsServer:
    def __init__(self, socket_path: str, journal_path: Optional[str] = None,
                 advertise_host: Optional[str] = None,
                 external_store_address: Optional[str] = None,
                 on_storage_failure=None):
        self.server = RpcServer(socket_path, name="gcs",
                                advertise_host=advertise_host)
        self.server.register_all(self)
        self.server.on_disconnect = self._on_disconnect
        # persistence ladder (gcs_storage.py): external store > local
        # journal > memory-only. With an external store the head node's
        # DISK is expendable — a replacement GCS anywhere re-seeds from
        # the store (ref: redis_store_client.h:111 + gcs_init_data.h)
        self._remote_store: Optional[RemoteStoreClient] = None
        self._on_storage_failure = on_storage_failure
        self._storage_health_task: Optional[asyncio.Task] = None
        self._node_health_task: Optional[asyncio.Task] = None
        if external_store_address:
            self._remote_store = RemoteStoreClient(external_store_address)
            self.storage = Storage(journal_path, remote=self._remote_store)
        else:
            self.storage = Storage(journal_path)
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}  # (namespace, name)
        self.jobs: Dict[JobID, dict] = {}
        self.placement_groups: Dict[PlacementGroupID, dict] = {}
        self._pg_tasks: Dict[PlacementGroupID, asyncio.Task] = {}
        self._pg_raylet_clients: Dict[str, Any] = {}
        self._pg_waiters: Dict[PlacementGroupID, List[asyncio.Future]] = {}
        # object directory: oid -> set of node ids holding a sealed copy
        # (the ownership-based-object-directory role, ref:
        # src/ray/object_manager/ownership_based_object_directory.h — here the
        # GCS keeps the authoritative location view; owners cache it)
        self.object_locations: Dict[ObjectID, Set[NodeID]] = {}
        # pubsub: channel -> set of subscribed connections
        self._subs: Dict[str, Set[ServerConnection]] = {}
        self._node_conns: Dict[ServerConnection, NodeID] = {}
        self._driver_conns: Dict[ServerConnection, JobID] = {}
        self._driver_cleanup_timers: Dict[JobID, asyncio.Task] = {}
        # observability tables (in-memory, bounded; not journaled)
        self.metrics: Dict[tuple, dict] = {}
        self.task_events: Dict[Any, dict] = {}
        self.MAX_TASK_EVENTS = 10_000
        self.MAX_METRICS = 10_000
        # structured cluster events (ref: src/ray/util/event.h +
        # _private/event/export_event_logger.py — severity-tagged
        # lifecycle records the dashboard event module surfaces)
        import collections as _collections

        self.events: "_collections.deque" = _collections.deque(maxlen=5000)
        # stall sentinel: collective/barrier arrival tables. Key
        # (group, step) -> record with per-rank clock-corrected arrival
        # timestamps; the collective watchdog flags records with
        # some-but-not-all arrivals past the deadline, and completed
        # steps roll their arrival-skew histogram into per-host
        # straggler scores.
        self.collectives: Dict[tuple, dict] = {}
        self.MAX_COLLECTIVES = 2000
        self._collective_waiters: Dict[tuple, list] = {}
        # host key (node hex, or reported host name) -> skew aggregates
        self.straggler_stats: Dict[str, dict] = {}
        self._collective_watchdog_task: Optional[asyncio.Task] = None
        # SLO observability plane (ray_tpu/slo.py): ring-buffered time
        # series of the aggregated metrics view + burn-rate monitor,
        # both fed by _slo_loop on the evaluation tick. Built lazily in
        # start() so config overrides applied at init are honored.
        self.series_store = None
        self.slo_monitor = None
        self._slo_task: Optional[asyncio.Task] = None
        # training goodput plane (ray_tpu/train/telemetry.py): per-job
        # ledgers folding rank step reports into productive vs badput
        # chip-seconds; fed by handle_train_report, surfaced through
        # handle_train_status and the _train_metrics synthetics
        self.train_ledgers: Dict[str, Any] = {}
        self.MAX_TRAIN_JOBS = 64
        # black-box plane (_private/blackbox.py): session dir derived
        # from the journal location (flight files / bundles / event
        # journal live next to it); the GCS keeps its own flight ring,
        # checkpoints durable observability state, and sweeps corpse
        # flight files when it declares a node dead.
        self.session_dir: Optional[str] = (
            os.path.dirname(journal_path) if journal_path else None)
        self.started_at = time.time()
        self._blackbox = None
        self._events_journal = None
        self._obs_task: Optional[asyncio.Task] = None
        # per-(node, role, reason, signal) crash counter — the
        # process_crashes_total Prometheus series
        self.crash_counts: Dict[tuple, int] = {}
        # clock offsets recovered from the last obs checkpoint (nodes
        # are not restored across restarts; postmortem still needs the
        # dead fleet's offsets to clock-correct its timeline)
        self._restored_clock_offsets: Dict[str, float] = {}
        self._last_diag_t = 0.0
        # node registration times (process_uptime_seconds source; a
        # raylet restart re-registers and resets its clock)
        self._node_first_seen: Dict[str, float] = {}
        self._next_job = 1
        if self._remote_store is None:
            self._restore_tables()
        # else: tables restore in start(), after the async snapshot load

    # ---- journal-backed table persistence (the Redis-persistence analog:
    #      gcs_table_storage.h + gcs_init_data.h restart rebuild) ----
    def _persist(self, table: str, key: str, obj: Any) -> None:
        self.storage.put("__table_" + table, key, pickle.dumps(obj))

    def _unpersist(self, table: str, key: str) -> None:
        self.storage.delete("__table_" + table, key)

    def _restore_tables(self) -> None:
        """Rebuild actor/PG/job tables from the journal on restart. Nodes
        are NOT restored — raylets re-register and their liveness is
        re-derived from fresh connections. Restored actor addresses may be
        stale; callers re-resolve through actor_failed on first contact."""
        for key in self.storage.keys("__table_actors"):
            info: ActorInfo = pickle.loads(
                self.storage.get("__table_actors", key))
            self.actors[info.actor_id] = info
            if info.name:
                self.named_actors[(info.namespace, info.name)] = info.actor_id
        for key in self.storage.keys("__table_pgs"):
            pg = pickle.loads(self.storage.get("__table_pgs", key))
            self.placement_groups[pg["pg_id"]] = pg
        for key in self.storage.keys("__table_jobs"):
            job_id, job = pickle.loads(self.storage.get("__table_jobs", key))
            self.jobs[job_id] = job
            self._next_job = max(self._next_job, int(key) + 1)

    async def start(self):
        if self._remote_store is not None:
            # seed tables from the external store BEFORE listening — a
            # client must never observe a half-restored GCS
            await self._remote_store.connect()
            await self.storage.load_remote()
            self._restore_tables()
            self._storage_health_task = asyncio.ensure_future(
                self._storage_failure_detector())
        await self.server.start()
        from .config import global_config

        if global_config().health_check_timeout_ms > 0:
            self._node_health_task = asyncio.ensure_future(
                self._node_health_loop())
        if global_config().collective_watchdog_interval_s > 0:
            self._collective_watchdog_task = asyncio.ensure_future(
                self._collective_watchdog_loop())
        cfg = global_config()
        if cfg.metrics_series_enabled and cfg.slo_eval_interval_s > 0:
            from ..slo import (SeriesStore, SloMonitor, default_policies,
                               parse_specs)

            self.series_store = SeriesStore(
                max_samples=cfg.metrics_series_max_samples,
                min_interval_s=cfg.metrics_series_min_interval_s,
                max_series=cfg.metrics_series_max_series)
            try:
                specs = parse_specs(cfg.slo_specs)
            except Exception as e:
                specs = []
                self._event("slo", "ERROR",
                            f"invalid slo_specs config, monitor empty: {e}")
            self.slo_monitor = SloMonitor(specs, default_policies(cfg))
            self._slo_task = asyncio.ensure_future(self._slo_loop())
        # durable observability: reload the last checkpoint (series
        # rings, SLO alert state, cumulative metrics table, task events)
        # so `cli slo`/`cli timeline` span the restart, then start
        # checkpointing ourselves
        self._restore_obs_checkpoint(cfg)
        if cfg.obs_checkpoint_interval_s > 0:
            self._obs_task = asyncio.ensure_future(
                self._obs_checkpoint_loop())
        if cfg.blackbox_enabled and self.session_dir:
            from . import blackbox

            self._blackbox = blackbox.FlightRecorder(
                "gcs", self.session_dir,
                ident=self.server.address,
                ring_size=cfg.blackbox_ring_size,
                flush_interval_s=cfg.blackbox_flush_interval_s,
                inflight_provider=self._blackbox_inflight,
            ).start()
        # restored placement groups that never finished reserving resume
        # scheduling now that the loop is live (restart recovery)
        for pg in self.placement_groups.values():
            if pg["state"] in ("PENDING", "RESCHEDULING"):
                self._kick_pg_scheduler(pg["pg_id"])

    # ---- black-box plane: flight ring + durable observability ----
    def _blackbox_inflight(self) -> list:
        """The GCS's in-flight view for its own flight ring: RUNNING
        tasks and non-terminal actors (what a head-death postmortem
        needs to implicate)."""
        out = []
        for rec in self.task_events.values():
            if rec.get("state") == "RUNNING":
                out.append({"kind": "task",
                            "task_id": str(rec.get("task_id")),
                            "name": rec.get("name", "")})
        for actor in self.actors.values():
            if actor.state in (ALIVE, PENDING_CREATION, RESTARTING):
                out.append({"kind": "actor",
                            "actor_id": actor.actor_id.hex(),
                            "class_name": actor.class_name,
                            "state": actor.state})
        return out[:200]

    def _restore_obs_checkpoint(self, cfg) -> None:
        raw = self.storage.get("__obs", "checkpoint")
        if not raw:
            return
        try:
            snap = pickle.loads(raw)
        except Exception as e:
            self._event("blackbox", "WARNING",
                        f"obs checkpoint unreadable, starting cold: {e!r}")
            return
        now = time.time()
        # cumulative per-worker metric values: restoring them means the
        # next worker report lands as a normal delta on top, so the
        # aggregated counters never step backwards across the restart
        # (no windowed_increase reset artifact)
        for key, entry in (snap.get("metrics") or {}).items():
            if len(self.metrics) >= self.MAX_METRICS:
                break
            self.metrics.setdefault(key, entry)
        for task_id, rec in (snap.get("task_events") or {}).items():
            if len(self.task_events) >= self.MAX_TASK_EVENTS:
                break
            self.task_events.setdefault(task_id, rec)
        self._restored_clock_offsets = dict(
            snap.get("clock_offsets") or {})
        # goodput ledgers: cumulative badput/rework accounting must
        # survive a head restart like every other counter here
        for job, state in (snap.get("train") or {}).items():
            try:
                from ..train.telemetry import GoodputLedger

                ledger = GoodputLedger(job)
                ledger.load(state)
                self.train_ledgers.setdefault(job, ledger)
            except Exception:  # graftlint: ignore[swallow] — one bad
                continue  # ledger must not poison the restore
        restored_series = 0
        if self.series_store is not None and snap.get("series"):
            restored_series = self.series_store.load(snap["series"])
        if self.slo_monitor is not None and snap.get("slo"):
            self.slo_monitor.load(snap["slo"], now=now,
                                  grace_s=cfg.slo_restore_grace_s)
        self._event(
            "blackbox", "INFO",
            f"observability state restored from checkpoint "
            f"(written {now - snap.get('written_at', now):.1f}s ago: "
            f"{restored_series} series, "
            f"{len(snap.get('task_events') or {})} task events)",
            kind="obs_restore", written_at=snap.get("written_at"))

    def _obs_checkpoint_once(self):
        """Persist the observability plane through the storage seam
        (journal or remote store — whatever the GCS already trusts)."""
        from .blackbox import ObsCheckpointInfo

        now = time.time()
        snap = {
            "version": 1,
            "written_at": now,
            "series": (self.series_store.dump()
                       if self.series_store is not None else None),
            "slo": (self.slo_monitor.dump()
                    if self.slo_monitor is not None else None),
            "metrics": dict(self.metrics),
            "task_events": dict(self.task_events),
            "clock_offsets": {
                info.node_id.hex(): info.clock_offset
                for info in self.nodes.values()},
            "train": {job: ledger.dump()
                      for job, ledger in self.train_ledgers.items()},
        }
        self.storage.put("__obs", "checkpoint", pickle.dumps(snap))
        return ObsCheckpointInfo(
            written_at=now,
            series=len(self.series_store or ()),
            slo_specs=(len(self.slo_monitor.specs)
                       if self.slo_monitor is not None else 0),
            task_events=len(self.task_events),
            metrics=len(self.metrics))

    async def _obs_checkpoint_loop(self):
        from .config import global_config

        period = max(1.0, global_config().obs_checkpoint_interval_s)
        while True:
            await asyncio.sleep(period)
            try:
                self._obs_checkpoint_once()
            except Exception:  # graftlint: ignore[swallow] — a failed
                pass  # checkpoint must not kill the periodic loop

    async def handle_obs_checkpoint(self, payload, conn):
        """Force a checkpoint now (tests, pre-restart flushes)."""
        return self._obs_checkpoint_once()

    async def handle_list_incidents(self, payload, conn):
        """Crash-bundle summaries + recent crash/blackbox events (the
        dashboard Incidents panel / `cli postmortem --live` source)."""
        from . import blackbox

        bundles = (blackbox.bundle_infos(self.session_dir)
                   if self.session_dir else [])
        limit = int(payload.get("limit", 100))
        events = [e for e in self.events
                  if e.get("source") in ("blackbox", "NODE")
                  or e.get("kind") in ("fast_burn", "slow_burn")]
        return {
            "session_dir": self.session_dir or "",
            "bundles": bundles[-limit:],
            "events": events[-limit:],
            "crash_counts": [
                {"node": k[0], "role": k[1], "reason": k[2],
                 "signal": k[3], "count": n}
                for k, n in self.crash_counts.items()],
        }

    async def handle_report_crash(self, payload, conn):
        """A raylet swept a worker corpse: count it, log it, and name
        the in-flight work in the event stream."""
        node = str(payload.get("node_id", ""))[:12]
        key = (node, payload.get("role", "worker"),
               payload.get("reason", "unknown"),
               payload.get("signal", ""))
        self.crash_counts[key] = self.crash_counts.get(key, 0) + 1
        inflight = payload.get("inflight") or []
        names = ", ".join(
            f"{str(r.get('task_id') or r.get('request_id') or '?')[:12]}"
            f" ({r.get('fn') or r.get('kind') or '?'})"
            for r in inflight[:5]) or "nothing in flight"
        self._event(
            "blackbox", "ERROR",
            f"{payload.get('role', 'worker')} pid "
            f"{payload.get('pid')} on node {node} crashed "
            f"({payload.get('reason', 'unknown')}): {names}",
            kind="process_crash", **{
                k: payload.get(k) for k in
                ("role", "pid", "node_id", "reason", "signal",
                 "bundle_path", "inflight")})
        return True

    async def _node_health_loop(self):
        """ACTIVE node liveness probing (ref: gcs_health_check_manager.h:45
        — periodic per-node probe + consecutive-failure threshold).
        Socket disconnect alone misses wedged-but-connected raylets
        (SIGSTOP, half-open TCP, a livelocked event loop): each round
        calls ``health`` on every alive raylet with a timeout; after
        health_check_failure_threshold consecutive misses the node is
        declared dead through the same _mark_node_dead path a disconnect
        takes (actors failed, objects reaped/lineage-rebuilt, PG bundles
        rescheduled)."""
        from .config import global_config

        cfg = global_config()
        period = max(0.05, cfg.health_check_period_ms / 1000.0)
        timeout = max(0.05, cfg.health_check_timeout_ms / 1000.0)
        misses: Dict[NodeID, int] = {}
        inflight: Dict[NodeID, asyncio.Task] = {}
        while True:
            await asyncio.sleep(period)
            for node_id in [n for n in inflight if n not in self.nodes]:
                inflight.pop(node_id).cancel()
            for node_id, info in list(self.nodes.items()):
                if not info.alive:
                    misses.pop(node_id, None)
                    continue
                prev = inflight.get(node_id)
                if prev is not None and not prev.done():
                    # at most ONE probe in flight per node: when this
                    # loop stalls (~5 s GC pause, saturated loop), the
                    # backlog of rounds must not fire as a burst of
                    # already-timed-out probes that alone cross the
                    # failure threshold and declare a live raylet dead
                    continue

                async def _probe(node_id=node_id, info=info):
                    try:
                        client = await asyncio.wait_for(
                            self._raylet_client(info.address), timeout)
                        ok = await client.call("health", {}, timeout=timeout)
                    except Exception:
                        ok = False
                    if ok:
                        misses.pop(node_id, None)
                        info.last_heartbeat_t = time.time()
                        return
                    n = misses.get(node_id, 0) + 1
                    misses[node_id] = n
                    if n >= cfg.health_check_failure_threshold:
                        misses.pop(node_id, None)
                        # drop AND close the cached client: a later
                        # reconnect must not reuse a half-open transport,
                        # and a wedged peer never closes its end — without
                        # close() the recv task and fd leak per death
                        stale = self._pg_raylet_clients.pop(
                            info.address, None)
                        if stale is not None:
                            try:
                                await stale.close()
                            except Exception:
                                pass
                        await self._mark_node_dead(
                            node_id, f"health check failed ({n} probes)")

                # probes run concurrently so one wedged node cannot
                # stretch the round for the others
                inflight[node_id] = asyncio.ensure_future(_probe())

    async def _storage_failure_detector(self):
        """Ping the external store; a sustained outage is fatal for the
        GCS (its writes are no longer durable), so after the threshold
        it reports and — like the reference — dies for a supervisor to
        restart it against a healthy store (ref:
        gcs_redis_failure_detector.h). Tests inject on_storage_failure
        to observe the trip without losing the process."""
        from .config import global_config

        cfg = global_config()
        period = max(0.2, cfg.health_check_period_ms / 1000.0)
        strikes = 0
        while True:
            await asyncio.sleep(period)
            if await self._remote_store.ping():
                strikes = 0
                continue
            strikes += 1
            if strikes >= cfg.health_check_failure_threshold:
                self._event("GCS_STORAGE", "ERROR",
                            "external store unreachable; GCS writes are "
                            "no longer durable",
                            address=self._remote_store.address)
                if self._on_storage_failure is not None:
                    self._on_storage_failure()
                    strikes = 0  # injected handler chose to continue
                else:
                    os._exit(1)

    async def stop(self):
        for task in list(self._pg_tasks.values()):
            task.cancel()
        if self._storage_health_task is not None:
            self._storage_health_task.cancel()
        if self._node_health_task is not None:
            self._node_health_task.cancel()
        if self._collective_watchdog_task is not None:
            self._collective_watchdog_task.cancel()
        if self._slo_task is not None:
            self._slo_task.cancel()
        if self._obs_task is not None:
            self._obs_task.cancel()
            try:
                self._obs_checkpoint_once()  # final flush before exit
            except Exception:  # graftlint: ignore[swallow] — shutdown
                pass  # path: best-effort durability only
        if self._blackbox is not None:
            self._blackbox.close(clean=True)
            self._blackbox = None
        if self._events_journal is not None:
            try:
                self._events_journal.close()
            except Exception:  # graftlint: ignore[swallow] — shutdown
                pass  # path: journal fd close is best-effort
            self._events_journal = None
        for client in self._pg_raylet_clients.values():
            try:
                await client.close()
            except Exception:
                pass
        await self.server.stop()
        if self._remote_store is not None:
            try:
                await self._remote_store.close()
            except Exception:
                pass
        self.storage.close()

    # ---- structured events (ref: util/event.h EventManager) ----
    def _event(self, source: str, severity: str, message: str,
               **fields) -> None:
        rec = {"timestamp": time.time(), "source": source,
               "severity": severity, "message": message, **fields}
        self.events.append(rec)
        self._journal_event(rec)
        if self._blackbox is not None:
            self._blackbox.record_event(rec)
        # streamed to subscribers too (dashboard live tail)
        background(self._publish("events", rec))

    def _journal_event(self, rec: dict) -> None:
        """Append-only JSONL event journal in the session dir: the
        dead-cluster source for `cli events --follow` and postmortem."""
        if self._events_journal is None:
            from .config import global_config

            if (not global_config().event_journal_enabled
                    or not self.session_dir):
                return
            from . import blackbox

            try:
                path = blackbox.events_journal_path(self.session_dir)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                self._events_journal = open(path, "a")
            except OSError:
                return
        try:
            self._events_journal.write(
                json.dumps(rec, default=str) + "\n")
            self._events_journal.flush()
        except (OSError, ValueError):
            pass  # closed mid-shutdown / disk full: in-memory deque wins

    async def handle_list_events(self, payload, conn):
        source = payload.get("source")
        severity = payload.get("severity")
        limit = int(payload.get("limit", 1000))
        out = [e for e in self.events
               if (not source or e["source"] == source)
               and (not severity or e["severity"] == severity)]
        return out[-limit:]

    async def handle_report_event(self, payload, conn):
        """Application/library events enter the same stream."""
        self._event(payload.get("source", "APP"),
                    payload.get("severity", "INFO"),
                    payload.get("message", ""),
                    **payload.get("fields", {}))
        return True

    # ---- stall sentinel: collective arrivals + straggler scores ----
    def _corrected_time(self, node_hex: str, t_local: float) -> float:
        """Apply the reporting node's NTP-style clock offset so arrival
        timestamps from different hosts compose on the GCS clock."""
        if node_hex:
            try:
                info = self.nodes.get(NodeID.from_hex(node_hex))
            except Exception:
                info = None
            if info is not None:
                return t_local + info.clock_offset
        return t_local

    def _prune_collectives(self) -> None:
        if len(self.collectives) <= self.MAX_COLLECTIVES:
            return
        done = [k for k, r in self.collectives.items()
                if r.get("completed_t") is not None]
        for k in done[:len(self.collectives) - self.MAX_COLLECTIVES]:
            self.collectives.pop(k, None)

    async def handle_collective_arrival(self, payload, conn):
        """One participant reached a collective/barrier step. Arrival
        timestamps are clock-corrected via the node table; a step whose
        arrivals complete rolls its skew histogram into the per-host
        straggler scores, and one left incomplete past its deadline is
        the collective watchdog's hung-collective signal."""
        group = payload["group"]
        step = int(payload["step"])
        rank = int(payload["rank"])
        size = int(payload["size"])
        node_hex = payload.get("node_id") or ""
        t = self._corrected_time(
            node_hex, float(payload.get("t") or time.time()))
        key = (group, step)
        rec = self.collectives.get(key)
        if rec is None:
            self._prune_collectives()
            rec = self.collectives[key] = {
                "group": group, "step": step,
                "op": payload.get("op", "barrier"), "size": size,
                "arrivals": {}, "first_t": t, "flagged": False,
                "completed_t": None,
                "deadline_s": float(payload.get("deadline_s") or 0.0),
            }
        rec["size"] = max(rec["size"], size)
        if payload.get("deadline_s"):
            dl = float(payload["deadline_s"])
            rec["deadline_s"] = (min(rec["deadline_s"], dl)
                                 if rec["deadline_s"] else dl)
        rec["arrivals"][rank] = {
            "t": t, "node_id": node_hex,
            "host": payload.get("host") or node_hex or f"rank{rank}",
        }
        rec["first_t"] = min(rec["first_t"], t)
        if (rec["completed_t"] is None
                and len(rec["arrivals"]) >= rec["size"]):
            rec["completed_t"] = time.time()
            self._roll_straggler_stats(rec)
        # wake collective_wait blockers (complete or not — they re-check)
        for fut in self._collective_waiters.pop(key, []):
            if not fut.done():
                fut.set_result(None)
        return {"arrived": len(rec["arrivals"]), "size": rec["size"],
                "complete": rec["completed_t"] is not None}

    async def handle_collective_wait(self, payload, conn):
        """Block until every rank reached (group, step) or timeout_s
        passes; the reply names missing ranks so the caller can raise a
        CollectiveTimeoutError that points at the hung participants."""
        key = (payload["group"], int(payload["step"]))
        deadline = time.monotonic() + float(payload.get("timeout_s", 30.0))
        while True:
            rec = self.collectives.get(key)
            if rec is not None and rec["completed_t"] is not None:
                return {"complete": True, "missing": [],
                        "arrived": len(rec["arrivals"]),
                        "size": rec["size"]}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                arrivals = rec["arrivals"] if rec else {}
                size = rec["size"] if rec else int(payload.get("size", 0))
                missing = sorted(set(range(size)) - set(arrivals))
                return {"complete": False, "missing": missing,
                        "arrived": len(arrivals), "size": size}
            fut = asyncio.get_event_loop().create_future()
            self._collective_waiters.setdefault(key, []).append(fut)
            try:
                await asyncio.wait_for(fut, min(remaining, 0.5))
            except asyncio.TimeoutError:
                pass
            finally:
                waiters = self._collective_waiters.get(key, [])
                if fut in waiters:
                    waiters.remove(fut)

    @staticmethod
    def _skew_bucket(late_s: float) -> str:
        for limit, label in ((0.001, "<1ms"), (0.01, "1-10ms"),
                             (0.1, "10-100ms"), (1.0, "100ms-1s"),
                             (10.0, "1-10s")):
            if late_s < limit:
                return label
        return ">10s"

    def _roll_straggler_stats(self, rec: dict) -> None:
        """Completed step: fold each rank's lateness (arrival - earliest
        arrival, clock-corrected) into its host's running aggregates.
        The straggler score read off these is the host's EMA lateness
        relative to the cluster mean — persistently-late hosts float to
        the top regardless of absolute step cadence."""
        arrivals = rec["arrivals"]
        if not arrivals:
            return
        t0 = min(a["t"] for a in arrivals.values())
        span = max(a["t"] for a in arrivals.values()) - t0
        rec["skew_s"] = span
        worst_rank = max(arrivals, key=lambda r: arrivals[r]["t"])
        for rank, a in arrivals.items():
            late = a["t"] - t0
            st = self._straggler_entry(a["host"], a.get("node_id"))
            self._fold_lateness(st, late)
            # only count "worst in step" when the skew is material —
            # someone is always last even in a perfectly healthy step
            if rank == worst_rank and span >= 0.005:
                st["worst_count"] += 1

    def _straggler_entry(self, host: str, node_id: Optional[str]) -> dict:
        st = self.straggler_stats.setdefault(host, {
            "host": host, "node_id": node_id or "", "steps": 0,
            "sum_lateness_s": 0.0, "max_lateness_s": 0.0,
            "ema_lateness_s": 0.0, "worst_count": 0, "hist": {}})
        if node_id:
            # scheduling deprioritization keys on node ids; collective
            # arrivals and direct reports both refresh the mapping
            st["node_id"] = node_id
        return st

    def _fold_lateness(self, st: dict, late: float) -> None:
        st["steps"] += 1
        st["sum_lateness_s"] += late
        st["max_lateness_s"] = max(st["max_lateness_s"], late)
        st["ema_lateness_s"] = (late if st["steps"] == 1
                                else 0.8 * st["ema_lateness_s"]
                                + 0.2 * late)
        bucket = self._skew_bucket(late)
        st["hist"][bucket] = st["hist"].get(bucket, 0) + 1

    async def handle_report_straggler(self, payload, conn):
        """Direct lateness sample outside the collective plane: a raylet
        watchdog flagging a RUNNING task past threshold, or an owner
        whose hedge beat the primary copy. Folds into the same per-host
        aggregates that drive straggler_scores, so task-plane stragglers
        deprioritize scheduling exactly like collective-skew ones."""
        node_id = payload.get("node_id") or ""
        # host key matches what collective arrivals use (node hex when no
        # host name rides the payload) so both planes fold into one entry
        host = payload.get("host") or node_id
        if not host:
            return False  # unattributable sample
        st = self._straggler_entry(host, node_id)
        self._fold_lateness(st, max(0.0, float(payload.get("late_s", 0.0))))
        if payload.get("source"):
            st.setdefault("sources", {})
            st["sources"][payload["source"]] = \
                st["sources"].get(payload["source"], 0) + 1
        return True

    async def handle_straggler_scores(self, payload, conn):
        stats = list(self.straggler_stats.values())
        if not stats:
            return []
        mean_ema = (sum(s["ema_lateness_s"] for s in stats)
                    / len(stats)) or 1e-9
        out = []
        for s in stats:
            rec = dict(s)
            rec["score"] = s["ema_lateness_s"] / max(mean_ema, 1e-9)
            out.append(rec)
        out.sort(key=lambda s: s["score"], reverse=True)
        return out

    async def handle_list_collectives(self, payload, conn):
        out = []
        for rec in self.collectives.values():
            r = {k: v for k, v in rec.items() if k != "arrivals"}
            r["arrived_ranks"] = sorted(rec["arrivals"])
            r["missing_ranks"] = sorted(
                set(range(rec["size"])) - set(rec["arrivals"]))
            out.append(r)
        return out

    async def _collective_watchdog_loop(self):
        """Flag collectives with some-but-not-all arrivals past their
        deadline: emit a WARNING "hung collective" event naming the
        missing ranks/hosts and pull Python stacks from the implicated
        nodes' workers."""
        from .config import global_config

        cfg = global_config()
        period = cfg.collective_watchdog_interval_s
        while True:
            await asyncio.sleep(period)
            now = time.time()
            for key, rec in list(self.collectives.items()):
                if rec["completed_t"] is not None or rec["flagged"]:
                    continue
                deadline = rec["deadline_s"] or cfg.collective_stall_timeout_s
                if now - rec["first_t"] < deadline:
                    continue
                rec["flagged"] = True
                try:
                    await self._flag_hung_collective(rec, deadline)
                except Exception:
                    pass  # forensics must never kill the watchdog

    def _rank_host_map(self, group: str) -> Dict[int, dict]:
        """rank -> {node_id, host} learned from every observed step of
        this group (a missing rank never arrived THIS step, but earlier
        steps tell us where it lives)."""
        mapping: Dict[int, dict] = {}
        for (g, _), rec in self.collectives.items():
            if g != group:
                continue
            for rank, a in rec["arrivals"].items():
                mapping[rank] = {"node_id": a["node_id"],
                                 "host": a["host"]}
        return mapping

    async def _flag_hung_collective(self, rec: dict, deadline: float):
        missing = sorted(set(range(rec["size"])) - set(rec["arrivals"]))
        known = self._rank_host_map(rec["group"])
        missing_hosts = {r: known.get(r, {}).get("host", "?")
                         for r in missing}
        # pull stacks from the missing ranks' nodes; when a rank's home
        # is unknown (it never arrived in any step), sweep all alive
        # nodes — the hung worker is on one of them
        node_hexes = {known[r]["node_id"] for r in missing
                      if r in known and known[r]["node_id"]}
        if not node_hexes:
            node_hexes = {n.node_id.hex() for n in self.nodes.values()
                          if n.alive}
        stacks = {}
        for node_hex in list(node_hexes)[:16]:
            info = None
            try:
                info = self.nodes.get(NodeID.from_hex(node_hex))
            except Exception:
                pass
            if info is None or not info.alive:
                continue
            try:
                client = await self._raylet_client(info.address)
                dump = await client.call("dump_worker_stacks", {},
                                         timeout=5)
                stacks[node_hex] = dump.get("workers", [])
            except Exception as e:
                stacks[node_hex] = [{"error": str(e) or repr(e)}]
        age = time.time() - rec["first_t"]
        self._event(
            "stall_sentinel", "WARNING",
            (f"hung collective {rec['group']} step {rec['step']} "
             f"({rec['op']}): {len(missing)}/{rec['size']} ranks missing "
             f"after {age:.1f}s — missing ranks {missing} "
             f"(hosts: {missing_hosts})"),
            kind="collective_stall", group=rec["group"],
            step=rec["step"], op=rec["op"], size=rec["size"],
            missing_ranks=missing, missing_hosts=missing_hosts,
            arrived_ranks=sorted(rec["arrivals"]), age_s=age,
            deadline_s=deadline, stacks=stacks)

    async def handle_list_stalls(self, payload, conn):
        """Cluster-wide stall view: hung collectives from this table,
        task/transfer stalls fanned in from every alive raylet."""
        out = {"tasks": [], "transfers": [], "collectives": []}
        for rec in self.collectives.values():
            if rec["flagged"] and rec["completed_t"] is None:
                out["collectives"].append({
                    "kind": "collective_stall",
                    "group": rec["group"], "step": rec["step"],
                    "op": rec["op"], "size": rec["size"],
                    "arrived_ranks": sorted(rec["arrivals"]),
                    "missing_ranks": sorted(
                        set(range(rec["size"])) - set(rec["arrivals"])),
                    "age_s": time.time() - rec["first_t"],
                })
        for info in list(self.nodes.values()):
            if not info.alive:
                continue
            try:
                client = await self._raylet_client(info.address)
                local = await client.call("list_stalls", {}, timeout=5)
            except Exception:
                continue
            out["tasks"].extend(local.get("tasks", []))
            out["transfers"].extend(local.get("transfers", []))
        return out

    async def handle_dump_all_stacks(self, payload, conn):
        """Fan dump_worker_stacks across every alive node (cli stacks
        without a node filter)."""
        out = []
        for info in list(self.nodes.values()):
            if not info.alive:
                continue
            try:
                client = await self._raylet_client(info.address)
                dump = await client.call("dump_worker_stacks", {},
                                         timeout=10)
            except Exception as e:
                dump = {"node_id": info.node_id.hex(),
                        "workers": [], "error": str(e) or repr(e)}
            out.append(dump)
        return out

    async def handle_profile_cluster(self, payload, conn):
        """Cluster-wide sampling burst (cli profile / dashboard
        flamegraph): start per-worker samplers on every matching alive
        raylet, sleep the window on the GCS loop, stop them, and merge
        the folded stacks — overall, per node, and per scheduling class
        (the ``task:<fn>`` roots the workers annotate)."""
        duration_s = float(payload.get("duration_s", 5.0))
        hz = float(payload.get("hz", 100.0))
        prefix = str(payload.get("node_id") or "")
        errors: List[dict] = []
        started = []
        for info in list(self.nodes.values()):
            if not info.alive:
                continue
            if prefix and not info.node_id.hex().startswith(prefix):
                continue
            try:
                client = await self._raylet_client(info.address)
                res = await client.call("profile_start_workers",
                                        {"hz": hz}, timeout=10)
                errors.extend({"node_id": info.node_id.hex(), **err}
                              for err in res.get("errors", []))
                started.append(info)
            except Exception as e:
                errors.append({"node_id": info.node_id.hex(),
                               "error": str(e) or repr(e)})
        await asyncio.sleep(max(0.0, duration_s))
        wall: Dict[str, int] = {}
        cpu: Dict[str, int] = {}
        per_node: Dict[str, Dict[str, int]] = {}
        samples = 0
        workers = 0
        for info in started:
            try:
                client = await self._raylet_client(info.address)
                dump = await client.call("profile_stop_workers", {},
                                         timeout=15)
            except Exception as e:
                errors.append({"node_id": info.node_id.hex(),
                               "error": str(e) or repr(e)})
                continue
            node_hex = dump.get("node_id", info.node_id.hex())
            node_wall = per_node.setdefault(node_hex, {})
            for snap in dump.get("workers", []):
                if snap.get("error"):
                    errors.append({"node_id": node_hex,
                                   "pid": snap.get("pid"),
                                   "error": snap["error"]})
                    continue
                workers += 1
                samples += int(snap.get("samples", 0))
                w = snap.get("wall", {})
                for key, n in w.items():
                    wall[key] = wall.get(key, 0) + n
                    node_wall[key] = node_wall.get(key, 0) + n
                for key, n in snap.get("cpu", {}).items():
                    cpu[key] = cpu.get(key, 0) + n
        # scheduling-class rollup: the worker annotates task-executing
        # threads with a ``task:<fn>`` root frame; everything else is
        # runtime/idle machinery.
        by_class: Dict[str, int] = {}
        for key, n in wall.items():
            root = key.split(";", 1)[0]
            cls = root[5:] if root.startswith("task:") else "(runtime)"
            by_class[cls] = by_class.get(cls, 0) + n
        return {"duration_s": duration_s, "hz": hz, "samples": samples,
                "workers": workers, "wall": wall, "cpu": cpu,
                "per_node": per_node, "by_class": by_class,
                "errors": errors}

    async def handle_memory_report(self, payload, conn):
        """Cluster memory attribution: fan ``node_memory_report`` to
        every alive raylet, merge the per-worker reference claims (plus
        the driver's, passed in the payload — the driver is not raylet-
        registered), and classify every live store object by ref-type:
        spilled > pending_task_arg > pinned > local_ref > borrowed >
        unreferenced. Pinned objects nobody claims that have out-aged
        ``memory_leak_age_s`` are flagged as leak suspects."""
        from .config import global_config

        leak_age_s = float(payload.get(
            "leak_age_s", global_config().memory_leak_age_s))
        limit = int(payload.get("limit", 200))
        errors: List[dict] = []
        node_reports = []
        for info in list(self.nodes.values()):
            if not info.alive:
                continue
            try:
                client = await self._raylet_client(info.address)
                rep = await client.call("node_memory_report", {},
                                        timeout=15)
                node_reports.append(rep)
            except Exception as e:
                errors.append({"node_id": info.node_id.hex(),
                               "error": str(e) or repr(e)})

        # ---- merge reference claims across every worker + the driver
        merged: Dict[str, dict] = {}

        def _absorb(label: str, claims: dict):
            for oid, c in (claims or {}).items():
                m = merged.setdefault(oid, {
                    "local_refs": 0, "task_deps": 0,
                    "owners": [], "borrowers": 0})
                m["local_refs"] += int(c.get("local_refs", 0))
                m["task_deps"] += int(c.get("task_deps", 0))
                if c.get("owned"):
                    m["owners"].append(label)
                if c.get("borrowed_from"):
                    m["borrowers"] += 1

        worker_summaries = []
        for rep in node_reports:
            node_hex = rep.get("node_id", "")
            for wrep in rep.get("workers", []):
                if wrep.get("error"):
                    errors.append({"node_id": node_hex,
                                   "pid": wrep.get("pid"),
                                   "error": wrep["error"]})
                label = (wrep.get("address")
                         or "pid:%s" % wrep.get("pid"))
                _absorb(label, wrep.get("claims"))
                worker_summaries.append({
                    "node_id": node_hex,
                    "worker_id": wrep.get("worker_id", ""),
                    "address": wrep.get("address", ""),
                    "pid": wrep.get("pid"),
                    "mode": wrep.get("mode", ""),
                    "num_inflight_tasks": wrep.get(
                        "num_inflight_tasks", 0),
                    "heap": wrep.get("heap", {}),
                    "hbm": wrep.get("hbm", []),
                    "memory_store": wrep.get("memory_store", {}),
                })
        driver = payload.get("driver") or {}
        if driver:
            _absorb("driver", driver.get("claims"))
            worker_summaries.append({
                "node_id": "", "worker_id": driver.get("worker_id", ""),
                "address": driver.get("address", "driver"),
                "pid": driver.get("pid"), "mode": "driver",
                "num_inflight_tasks": driver.get("num_inflight_tasks", 0),
                "heap": driver.get("heap", {}),
                "hbm": driver.get("hbm", []),
                "memory_store": driver.get("memory_store", {}),
            })

        # ---- classify every store object
        def _ref_type(meta: dict, claim: Optional[dict]) -> str:
            if meta.get("spilled"):
                return "spilled"
            if claim and claim.get("task_deps", 0) > 0:
                return "pending_task_arg"
            if meta.get("pinned", 0) > 0:
                return "pinned"
            if claim and claim.get("local_refs", 0) > 0:
                return "local_ref"
            if claim and claim.get("borrowers", 0) > 0:
                return "borrowed"
            return "unreferenced"

        nodes_out = []
        objects: List[dict] = []
        leak_suspects: List[dict] = []
        cluster_by_type: Dict[str, int] = {}
        cluster_used = 0
        cluster_spill = 0
        cluster_attr = 0
        for rep in node_reports:
            node_hex = rep.get("node_id", "")
            store = rep.get("store", {})
            by_type: Dict[str, int] = {}
            for oid, meta in store.get("objects", {}).items():
                claim = merged.get(oid)
                rtype = _ref_type(meta, claim)
                size = int(meta.get("size", 0))
                by_type[rtype] = by_type.get(rtype, 0) + size
                entry = {
                    "object_id": oid, "node_id": node_hex,
                    "size": size,
                    "age_s": round(float(meta.get("age_s", 0.0)), 1),
                    "pinned": int(meta.get("pinned", 0)),
                    "spilled": bool(meta.get("spilled")),
                    "ref_type": rtype,
                    "owners": list(claim["owners"]) if claim else [],
                }
                # leak suspect: pinned by the control plane, claimed by
                # nobody, and older than the leak threshold — the owner
                # likely died or dropped the ref without unpinning.
                unclaimed = (not claim
                             or (claim["local_refs"] == 0
                                 and claim["task_deps"] == 0))
                if (entry["pinned"] > 0 and unclaimed
                        and not entry["spilled"]
                        and entry["age_s"] > leak_age_s):
                    entry["leak_suspect"] = True
                    leak_suspects.append(entry)
                else:
                    entry["leak_suspect"] = False
                objects.append(entry)
            used = int(store.get("used_bytes", 0))
            spill = int(store.get("spill_bytes", 0))
            attr = sum(b for t, b in by_type.items()
                       if t not in ("unreferenced", "spilled"))
            cluster_used += used
            cluster_spill += spill
            cluster_attr += attr
            for t, b in by_type.items():
                cluster_by_type[t] = cluster_by_type.get(t, 0) + b
            nodes_out.append({
                "node_id": node_hex,
                "used_bytes": used,
                "capacity_bytes": int(store.get("capacity_bytes", 0)),
                "spill_bytes": spill,
                "num_objects": int(store.get("num_objects", 0)),
                "by_ref_type": by_type,
            })
        objects.sort(key=lambda o: o["size"], reverse=True)
        return {
            "nodes": nodes_out,
            "workers": worker_summaries,
            "objects": objects[:limit] if limit > 0 else objects,
            "leak_suspects": leak_suspects,
            "cluster": {
                "used_bytes": cluster_used,
                "spill_bytes": cluster_spill,
                "attributed_bytes": cluster_attr,
                "by_ref_type": cluster_by_type,
                "num_objects": len(objects),
                "attributed_fraction": (
                    cluster_attr / cluster_used
                    if cluster_used > 0 else 1.0),
            },
            "errors": errors,
        }

    # ---- pubsub ----
    async def _publish(self, channel: str, payload: Any):
        for conn in list(self._subs.get(channel, ())):
            await conn.push("pubsub:" + channel, payload)

    async def handle_subscribe(self, payload, conn):
        for channel in payload["channels"]:
            self._subs.setdefault(channel, set()).add(conn)
        return True

    async def handle_unsubscribe(self, payload, conn):
        for channel in payload["channels"]:
            conns = self._subs.get(channel)
            if conns is not None:
                conns.discard(conn)
                if not conns:
                    self._subs.pop(channel, None)
        return True

    async def _publish_actor(self, actor):
        """Actor updates go to per-actor subscribers (``actor:<hex>``)
        plus any blanket ``actor`` subscribers (dashboard, state API).
        Blanket delivery to every core worker would be O(actors x
        workers) pushes through this one loop at envelope depth (1k+
        actors); the reference pubsub indexes subscriptions per entity
        key for the same reason (ref: src/ray/pubsub/publisher.h
        SubscriptionIndex)."""
        payload = {"actor": actor}
        blanket = self._subs.get("actor", set())
        for conn in list(blanket):
            await conn.push("pubsub:actor", payload)
        key = "actor:" + actor.actor_id.hex()
        for conn in list(self._subs.get(key, ())):
            if conn not in blanket:
                await conn.push("pubsub:actor", payload)
        if actor.state == DEAD:
            # terminal: nobody will see another update on this key
            self._subs.pop(key, None)

    async def handle_publish(self, payload, conn):
        """Application-level pubsub fan-out (the reference's long-poll
        broadcast role, ref: python/ray/serve/_private/long_poll.py:66
        LongPollClient — here a plain push to every subscriber of the
        channel; Serve uses it to push config versions to routers and
        handles instead of having them poll)."""
        await self._publish(payload["channel"], payload["message"])
        return True

    async def _on_disconnect(self, conn):
        for subs in self._subs.values():
            subs.discard(conn)
        node_id = self._node_conns.pop(conn, None)
        if node_id is not None:
            await self._mark_node_dead(node_id, "raylet disconnected")
        job_id = self._driver_conns.pop(conn, None)
        if job_id is not None:
            # a dropped connection is only a HINT of driver death (network
            # blip, reconnect in flight): grant a grace window and cancel
            # if the driver re-registers. Clean exits send driver_exit
            # explicitly and skip the grace.
            self._schedule_driver_cleanup(job_id)

    def _schedule_driver_cleanup(self, job_id: JobID, grace_s: float = 10.0):
        if job_id in self._driver_cleanup_timers:
            return

        async def _later():
            try:
                await asyncio.sleep(grace_s)
                await self._on_driver_exit(job_id)
            finally:
                self._driver_cleanup_timers.pop(job_id, None)

        self._driver_cleanup_timers[job_id] = asyncio.ensure_future(_later())

    async def handle_register_driver(self, payload, conn):
        """Bind this connection to a driver's job: when the driver goes
        away, its non-detached actors are torn down (ref:
        gcs_actor_manager.cc OnJobFinished)."""
        job_id = payload["job_id"]
        self._driver_conns[conn] = job_id
        timer = self._driver_cleanup_timers.pop(job_id, None)
        if timer is not None:
            timer.cancel()  # driver reconnected within the grace window
        return True

    async def handle_driver_exit(self, payload, conn):
        """Explicit clean driver detach: immediate cleanup, no grace."""
        timer = self._driver_cleanup_timers.pop(payload["job_id"], None)
        if timer is not None:
            timer.cancel()
        self._driver_conns.pop(conn, None)
        await self._on_driver_exit(payload["job_id"])
        return True

    async def _on_driver_exit(self, job_id: JobID):
        for actor in list(self.actors.values()):
            if (actor.actor_id.job_id() == job_id and not actor.detached
                    and actor.state != DEAD):
                address = actor.address
                actor.max_restarts = 0
                actor.state = DEAD
                actor.death_cause = "creating driver exited"
                self._persist("actors", actor.actor_id.hex(), actor)
                await self._publish_actor(actor)
                if address:
                    background(self._kill_actor_process(address))

    async def _kill_actor_process(self, address: str):
        from .rpc import RpcClient

        try:
            client = RpcClient(address)
            await client.connect(timeout=2)
            await client.call("kill_self", {}, timeout=2)
            await client.close()
        except Exception:
            pass  # worker already gone

    # ---- nodes ----
    async def handle_register_node(self, payload, conn):
        info = NodeInfo(**payload)
        info.last_heartbeat_t = time.time()
        # re-registration (raylet restart) resets the uptime clock
        self._node_first_seen[info.node_id.hex()] = info.last_heartbeat_t
        self.nodes[info.node_id] = info
        self._node_conns[conn] = info.node_id
        await self._publish("node", {"event": "added", "node": info})
        self._event("NODE", "INFO", "node registered",
                    node_id=info.node_id.hex(), address=info.address)
        return {"nodes": list(self.nodes.values())}

    async def handle_get_all_nodes(self, payload, conn):
        return list(self.nodes.values())

    async def handle_report_resources(self, payload, conn):
        node_id = payload["node_id"]
        info = self.nodes.get(node_id)
        if info is not None:
            seq = payload.get("seq", 0)
            if seq and seq <= info.resource_seq:
                return True  # stale retry of an older report — ignore
            info.last_heartbeat_t = time.time()
            info.resource_seq = seq
            info.resources_available = payload["available"]
            info.pending_demands = payload.get("pending", [])
            await self._publish("resources", {
                "node_id": node_id, "available": payload["available"],
            })
        return True

    async def handle_drain_node(self, payload, conn):
        await self._mark_node_dead(payload["node_id"], payload.get("reason", "drained"))
        return True

    async def _mark_node_dead(self, node_id: NodeID, reason: str):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        await self._publish("node", {"event": "removed", "node_id": node_id, "reason": reason})
        self._event("NODE", "ERROR" if "died" in reason or "lost" in reason
                    else "INFO", f"node dead: {reason}",
                    node_id=node_id.hex())
        self._sweep_node_corpses(node_id, reason)
        # Fail actors on the dead node (ref: gcs_actor_manager OnNodeDead)
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (ALIVE, PENDING_CREATION):
                await self._actor_failed(actor, f"node {node_id} died: {reason}")
        # Objects whose last sealed copy lived on the dead node are lost;
        # consumers surface ObjectLostError (or reconstruct via lineage).
        lost = []
        for oid, nodes in list(self.object_locations.items()):
            nodes.discard(node_id)
            if not nodes:
                del self.object_locations[oid]
                lost.append(oid)
        for oid in lost:
            await self._publish("object", {"event": "lost", "object_id": oid})
        # Bundles reserved on the dead node are gone: put their placement
        # groups back on the scheduler to re-reserve elsewhere (ref:
        # gcs_placement_group_manager OnNodeDead -> RESCHEDULING)
        for pg in list(self.placement_groups.values()):
            hit = [i for i, nid in enumerate(pg["bundle_nodes"]) if nid == node_id]
            if hit:
                for i in hit:
                    pg["bundle_nodes"][i] = None
                if pg["state"] == "CREATED":
                    pg["state"] = "RESCHEDULING"
                await self._publish("placement_group", pg)
                self._kick_pg_scheduler(pg["pg_id"])

    def _sweep_node_corpses(self, node_id: NodeID, reason: str) -> None:
        """Heartbeat loss / disconnect declared a node dead: promote
        every flight file the corpse's processes left into crash bundles
        (a SIGKILL'd or silently-lost process dumps nothing itself —
        the survivor does it). Same-host sessions share the session
        dir, so the head can read the corpse's files directly."""
        if not self.session_dir:
            return
        from . import blackbox

        node_hex = node_id.hex()
        try:
            promoted = blackbox.sweep(
                self.session_dir, reason=f"node_death: {reason}",
                bundled_by="gcs", node_id=node_hex)
        except Exception:  # graftlint: ignore[swallow] — a failed sweep
            return  # must not break node-death handling
        for snap in promoted:
            key = (node_hex[:12], snap.get("role", "proc"),
                   "node_death", str(snap.get("signal", "")))
            self.crash_counts[key] = self.crash_counts.get(key, 0) + 1
            inflight = snap.get("inflight") or []
            names = ", ".join(
                str(r.get("task_id", r.get("request_id", "?")))[:12]
                for r in inflight[:5]) or "nothing in flight"
            self._event(
                "blackbox", "ERROR",
                f"swept crash bundle for {snap.get('role')} pid "
                f"{snap.get('pid')} on dead node {node_hex[:12]} "
                f"(in flight: {names})",
                kind="process_crash", role=snap.get("role"),
                pid=snap.get("pid"), node_id=node_hex,
                reason="node_death", bundle_path=snap.get("path"),
                inflight=inflight)

    # ---- jobs ----
    async def handle_register_job(self, payload, conn):
        job_id = JobID.from_int(self._next_job)
        job_num = self._next_job
        self._next_job += 1
        self.jobs[job_id] = {"config": payload.get("config", {}), "start_time": time.time(),
                             "driver_address": payload.get("driver_address", "")}
        self._persist("jobs", str(job_num), (job_id, self.jobs[job_id]))
        self._event("JOB", "INFO", "job registered", job_id=job_id.hex())
        return job_id

    async def handle_get_all_jobs(self, payload, conn):
        return self.jobs

    # ---- KV (function table etc.; ref: gcs_kv_manager.h) ----
    async def handle_kv_put(self, payload, conn):
        self.storage.put(payload["ns"], payload["key"], payload["value"])
        return True

    async def handle_kv_get(self, payload, conn):
        return self.storage.get(payload["ns"], payload["key"])

    async def handle_kv_del(self, payload, conn):
        return self.storage.delete(payload["ns"], payload["key"])

    async def handle_kv_keys(self, payload, conn):
        return self.storage.keys(payload["ns"], payload.get("prefix", ""))

    # ---- actors (ref: gcs_actor_manager.cc) ----
    async def handle_register_actor(self, payload, conn):
        info = ActorInfo(
            actor_id=payload["actor_id"],
            state=PENDING_CREATION,
            name=payload.get("name", ""),
            namespace=payload.get("namespace", ""),
            detached=payload.get("detached", False),
            owner_is_driver=payload.get("owner_is_driver", True),
            class_name=payload.get("class_name", ""),
            max_restarts=payload.get("max_restarts", 0),
            creation_spec=payload.get("creation_spec"),
        )
        if info.name:
            key = (info.namespace, info.name)
            existing = self.named_actors.get(key)
            if existing is not None and self.actors[existing].state != DEAD:
                raise ValueError(f"Actor name '{info.name}' already taken")
            self.named_actors[key] = info.actor_id
        if payload.get("subscribe"):
            # owner registers + subscribes to the keyed lifecycle channel
            # in one hop (half the creation-path RPCs; the subscription
            # is live before the PENDING_CREATION publish below)
            self._subs.setdefault(
                "actor:" + info.actor_id.hex(), set()).add(conn)
        self.actors[info.actor_id] = info
        self._persist("actors", info.actor_id.hex(), info)
        await self._publish_actor(info)
        self._event("ACTOR", "INFO", "actor registered",
                    actor_id=info.actor_id.hex(),
                    class_name=info.class_name, name=info.name)
        return True

    async def handle_actor_alive(self, payload, conn):
        actor = self.actors.get(payload["actor_id"])
        if actor is None:
            return False
        if actor.state == DEAD:
            # killed while still creating (driver exited, explicit kill):
            # do NOT resurrect — put the late-arriving worker down instead
            background(
                self._kill_actor_process(payload["address"]))
            return False
        actor.state = ALIVE
        actor.address = payload["address"]
        actor.node_id = payload.get("node_id")
        self._persist("actors", actor.actor_id.hex(), actor)
        await self._publish_actor(actor)
        return True

    async def handle_actor_failed(self, payload, conn):
        actor = self.actors.get(payload["actor_id"])
        if actor is not None:
            await self._actor_failed(actor, payload.get("cause", "worker died"))
        return True

    async def _actor_failed(self, actor: ActorInfo, cause: str):
        # restarts are owner-driven: an actor created DIRECTLY by a driver
        # that has since exited has nobody to resubmit its creation task, so
        # leaving it RESTARTING would hang every caller forever — mark it
        # DEAD instead. Actors created by other actors keep their worker
        # process as a live owner and restart normally. (GCS-driven restart
        # of orphaned detached actors is future work.)
        if (actor.owner_is_driver
                and actor.actor_id.job_id() not in self._driver_conns.values()
                and actor.num_restarts < actor.max_restarts):
            cause += " (creating driver exited; restart impossible)"
            actor.num_restarts = actor.max_restarts
        if actor.num_restarts < actor.max_restarts:
            actor.num_restarts += 1
            actor.state = RESTARTING
            actor.address = ""
            self._persist("actors", actor.actor_id.hex(), actor)
            await self._publish_actor(actor)
            self._event("ACTOR", "WARNING",
                        f"actor restarting ({actor.num_restarts}/"
                        f"{actor.max_restarts}): {cause}",
                        actor_id=actor.actor_id.hex(),
                        class_name=actor.class_name)
            # restart is driven by the owning core worker, which subscribes
            # to RESTARTING transitions and resubmits the creation task
        else:
            actor.state = DEAD
            actor.death_cause = cause
            actor.address = ""
            self._persist("actors", actor.actor_id.hex(), actor)
            await self._publish_actor(actor)
            self._event("ACTOR", "ERROR", f"actor died: {cause}",
                        actor_id=actor.actor_id.hex(),
                        class_name=actor.class_name)

    async def handle_kill_actor(self, payload, conn):
        actor = self.actors.get(payload["actor_id"])
        if actor is None:
            return False
        actor.max_restarts = 0  # no_restart
        if actor.state != DEAD:
            actor.state = DEAD
            actor.death_cause = payload.get("cause", "ray_tpu.kill")
            self._persist("actors", actor.actor_id.hex(), actor)
            await self._publish_actor(actor)
        return True

    async def handle_get_actor(self, payload, conn):
        if "actor_id" in payload:
            return self.actors.get(payload["actor_id"])
        key = (payload.get("namespace", ""), payload["name"])
        actor_id = self.named_actors.get(key)
        return self.actors.get(actor_id) if actor_id is not None else None

    async def handle_list_actors(self, payload, conn):
        return list(self.actors.values())

    # ---- placement groups (ref: gcs_placement_group_manager.h +
    #      gcs_placement_group_scheduler.h: the GCS owns bundle placement and
    #      drives the raylets' two-phase reserve/commit protocol) ----
    async def handle_create_placement_group(self, payload, conn):
        pg_id = payload["pg_id"]
        bundles = payload["bundles"]
        if not bundles or any(not b for b in bundles):
            raise ValueError("placement group bundles must be non-empty dicts")
        self.placement_groups[pg_id] = {
            "pg_id": pg_id, "bundles": bundles,
            "strategy": payload["strategy"], "state": "PENDING",
            "name": payload.get("name", ""),
            # one entry per bundle: NodeID once reserved, None while pending
            "bundle_nodes": [None] * len(bundles),
        }
        self._persist("pgs", pg_id.hex(), self.placement_groups[pg_id])
        await self._publish("placement_group", self.placement_groups[pg_id])
        self._kick_pg_scheduler(pg_id)
        return True

    def _kick_pg_scheduler(self, pg_id: PlacementGroupID) -> None:
        task = self._pg_tasks.get(pg_id)
        if task is not None and not task.done():
            return
        self._pg_tasks[pg_id] = asyncio.ensure_future(self._schedule_pg_loop(pg_id))

    async def _schedule_pg_loop(self, pg_id: PlacementGroupID) -> None:
        """Retry placement until the PG is fully reserved or removed (ref:
        gcs_placement_group_manager.h pending queue + retry on resource change;
        here a per-PG task with a short poll — cluster views are tiny)."""
        try:
            while True:
                pg = self.placement_groups.get(pg_id)
                if pg is None or pg["state"] in ("CREATED", "REMOVED"):
                    return
                ok = await self._try_schedule_pg(pg)
                if self.placement_groups.get(pg_id) is not pg:
                    # removed while the 2PC was in flight: the remove handler
                    # could not see these fresh reservations — roll them back
                    # here so no raylet resources leak
                    for i, nid in enumerate(pg["bundle_nodes"]):
                        if nid is not None:
                            await self._cancel_bundle(pg_id, i, nid)
                    return
                if ok:
                    pg["state"] = "CREATED"
                    self._persist("pgs", pg_id.hex(), pg)
                    self._wake_pg_waiters(pg_id)
                    await self._publish("placement_group", pg)
                    return
                await asyncio.sleep(0.1)
        finally:
            self._pg_tasks.pop(pg_id, None)

    def _wake_pg_waiters(self, pg_id) -> None:
        for fut in self._pg_waiters.pop(pg_id, []):
            if not fut.done():
                fut.set_result(None)

    def _plan_bundles(self, pg: dict) -> Optional[List[NodeID]]:
        """Pick a node per unplaced bundle per strategy, against the current
        resource view (ref: policy/bundle_scheduling_policy.h:82-106). Returns
        a full bundle->node list, or None if infeasible right now. The plan is
        validated authoritatively by reserve_bundle on each raylet."""
        from .task_spec import ResourceSet

        avail = {nid: ResourceSet(dict(info.resources_available))
                 for nid, info in self.nodes.items() if info.alive}
        placed: List[Optional[NodeID]] = list(pg["bundle_nodes"])
        # already-reserved bundles keep their node; their resources are
        # already deducted from the reporting raylet's availability
        strategy = pg["strategy"]
        used_nodes = {n for n in placed if n is not None}
        todo = [i for i, n in enumerate(placed) if n is None or n not in avail]
        if strategy == "STRICT_PACK":
            # every bundle on one node (respect any existing reservation)
            candidates = list(used_nodes) if used_nodes else list(avail)
            for nid in candidates:
                if nid not in avail:
                    continue
                trial = avail[nid].copy()
                ok = True
                for i in todo:
                    req = ResourceSet(pg["bundles"][i])
                    if not req.fits(trial):
                        ok = False
                        break
                    trial.subtract(req)
                if ok:
                    for i in todo:
                        placed[i] = nid
                    return placed  # type: ignore[return-value]
            return None
        # TPU slice-aware placement (the TPU-first substitution of
        # SURVEY §7.1.2): a spread PG whose bundles all request TPU maps
        # onto ONE ICI slice, bundle k on the slice's k-th host in
        # host_index order — the gang becomes a physical sub-cube whose
        # collectives ride ICI, not DCN (ref:
        # policy/bundle_scheduling_policy.h:82-106 +
        # accelerators/tpu.py:401-403's slice-head gang resource,
        # promoted from resource-string convention into the scheduler).
        if (todo and strategy in ("SPREAD", "STRICT_SPREAD")
                and all(ResourceSet(pg["bundles"][i]).get("TPU") > 0
                        for i in todo)):
            sliced = self._plan_bundles_on_slice(pg, avail, placed, todo)
            if sliced is not None:
                return sliced
            # no slice can host the whole gang: generic placement below
        # place most-constrained bundles first (fewest feasible nodes) so a
        # bundle needing a rare resource isn't starved by flexible ones
        todo.sort(key=lambda i: sum(
            1 for a in avail.values() if ResourceSet(pg["bundles"][i]).fits(a)))
        for i in todo:
            req = ResourceSet(pg["bundles"][i])
            feasible = [nid for nid, a in avail.items() if req.fits(a)]
            if strategy == "STRICT_SPREAD":
                feasible = [nid for nid in feasible if nid not in used_nodes]
            if not feasible:
                return None
            if strategy == "PACK":
                # prefer nodes already carrying bundles, then most-utilized
                feasible.sort(key=lambda nid: (
                    nid not in used_nodes,
                    sum(avail[nid].res.values())))
            elif strategy in ("SPREAD", "STRICT_SPREAD"):
                # prefer fresh, least-loaded nodes
                feasible.sort(key=lambda nid: (
                    nid in used_nodes,
                    -sum(avail[nid].res.values())))
            nid = feasible[0]
            placed[i] = nid
            avail[nid].subtract(req)
            used_nodes.add(nid)
        return placed  # type: ignore[return-value]

    def _plan_bundles_on_slice(self, pg: dict, avail: dict,
                               placed: list, todo: list):
        """Assign the unplaced bundles of a TPU gang to the hosts of one
        ICI slice in host_index order. Prefers the smallest slice that
        fits (tight sub-cubes leave big slices for big gangs). Returns
        the full placement list or None."""
        from .task_spec import ResourceSet

        used = {n for n in placed if n is not None}
        slices: Dict[str, list] = {}
        for nid, info in self.nodes.items():
            if info.alive and info.slice_name and nid in avail:
                slices.setdefault(info.slice_name, []).append(
                    (info.host_index, nid))
        if used:
            # bundles already reserved pin the gang to their slice
            names = {self.nodes[n].slice_name for n in used
                     if n in self.nodes}
            if len(names) != 1 or "" in names:
                return None
            slices = {k: v for k, v in slices.items() if k in names}
        best = None
        for name in sorted(slices):
            hosts = sorted(slices[name])
            free_hosts = [nid for _, nid in hosts if nid not in used]
            if len(free_hosts) < len(todo):
                continue
            trial = {nid: avail[nid].copy() for nid in free_hosts}
            assign = {}
            ok = True
            for k, i in enumerate(sorted(todo)):
                nid = free_hosts[k]  # bundle k -> k-th host by host_index
                req = ResourceSet(pg["bundles"][i])
                if not req.fits(trial[nid]):
                    ok = False
                    break
                trial[nid].subtract(req)
                assign[i] = nid
            if ok and (best is None or len(hosts) < best[0]):
                best = (len(hosts), assign)
        if best is None:
            return None
        out = list(placed)
        for i, nid in best[1].items():
            out[i] = nid
        return out

    async def _try_schedule_pg(self, pg: dict) -> bool:
        plan = self._plan_bundles(pg)
        if plan is None:
            return False
        pg_id = pg["pg_id"]
        newly = [(i, nid) for i, nid in enumerate(plan)
                 if pg["bundle_nodes"][i] != nid]
        # phase 1: reserve every new bundle; roll back all of them on any miss
        reserved: List[Tuple[int, NodeID]] = []
        ok = True
        for i, nid in newly:
            info = self.nodes.get(nid)
            if info is None or not info.alive:
                ok = False
                break
            try:
                granted = await self._raylet_call(
                    info.address, "reserve_bundle", {
                        "pg_id": pg_id, "bundle_index": i,
                        "resources": pg["bundles"][i]})
            except Exception:
                granted = False
            if not granted:
                ok = False
                break
            reserved.append((i, nid))
        if not ok:
            for i, nid in reserved:
                await self._cancel_bundle(pg_id, i, nid)
            return False
        # phase 2: commit (ref: placement_group_resource_manager.h 2PC);
        # a failed commit means the raylet lost the reservation — do not
        # record the bundle as placed, retry the whole group
        all_committed = True
        for i, nid in newly:
            committed = False
            info = self.nodes.get(nid)
            if info is not None:
                try:
                    committed = bool(await self._raylet_call(
                        info.address, "commit_bundle",
                        {"pg_id": pg_id, "bundle_index": i}))
                except Exception:
                    committed = False
            if committed:
                pg["bundle_nodes"][i] = nid
            else:
                await self._cancel_bundle(pg_id, i, nid)
                all_committed = False
        return all_committed

    async def _cancel_bundle(self, pg_id, bundle_index, node_id) -> None:
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        try:
            await self._raylet_call(info.address, "cancel_bundle", {
                "pg_id": pg_id, "bundle_index": bundle_index})
        except Exception:  # graftlint: ignore[swallow]
            # rollback best-effort: the raylet may already be dead, and
            # its bundle ledger resets with it — nothing to unwind
            pass

    async def handle_remove_placement_group(self, payload, conn):
        # NOTE: the scheduler task is NOT canceled — interrupting it mid-2PC
        # would strand reservations; _schedule_pg_loop detects the removal
        # after its in-flight attempt and rolls back itself
        pg = self.placement_groups.pop(payload["pg_id"], None)
        if pg is not None:
            for i, nid in enumerate(pg["bundle_nodes"]):
                if nid is not None:
                    await self._cancel_bundle(pg["pg_id"], i, nid)
            pg["state"] = "REMOVED"
            self._unpersist("pgs", pg["pg_id"].hex())
            self._wake_pg_waiters(pg["pg_id"])
            await self._publish("placement_group", pg)
        return True

    async def handle_get_placement_group(self, payload, conn):
        if "pg_id" in payload:
            return self.placement_groups.get(payload["pg_id"])
        for pg in self.placement_groups.values():
            if pg["name"] and pg["name"] == payload.get("name"):
                return pg
        return None

    async def handle_list_placement_groups(self, payload, conn):
        return list(self.placement_groups.values())

    async def handle_wait_placement_group_ready(self, payload, conn):
        """Block until the PG is fully reserved, removed, or timeout (the
        driver-side `pg.ready()` / `pg.wait()` backend). Waiters park on a
        future resolved at state transitions — no polling."""
        pg_id = payload["pg_id"]
        timeout = payload.get("timeout")
        deadline = None if timeout is None else asyncio.get_event_loop().time() + timeout
        while True:
            pg = self.placement_groups.get(pg_id)
            if pg is None:
                return {"status": "removed"}
            if pg["state"] == "CREATED":
                nodes = []
                for nid in pg["bundle_nodes"]:
                    info = self.nodes.get(nid)
                    nodes.append((nid, info.address if info else ""))
                return {"status": "ready", "bundle_nodes": nodes}
            fut = asyncio.get_event_loop().create_future()
            self._pg_waiters.setdefault(pg_id, []).append(fut)
            try:
                remaining = (None if deadline is None
                             else deadline - asyncio.get_event_loop().time())
                if remaining is not None and remaining <= 0:
                    return {"status": "timeout"}
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return {"status": "timeout"}
            finally:
                waiters = self._pg_waiters.get(pg_id, [])
                if fut in waiters:
                    waiters.remove(fut)

    async def _raylet_client(self, address: str):
        from .rpc import RpcClient

        client = self._pg_raylet_clients.get(address)
        if client is None or client.closed:
            client = RpcClient(address)
            await client.connect(timeout=10)
            self._pg_raylet_clients[address] = client
        return client

    async def _raylet_call(self, address: str, method: str, payload: dict):
        """Outbound raylet RPC bounded by gcs_rpc_timeout_s.

        The GCS event loop serves every control-plane handler; one
        unresponsive raylet (wedged host, partitioned network) must
        surface as GcsTimeoutError at the call site — never park a
        scheduler loop forever."""
        from ..exceptions import GcsTimeoutError
        from .config import global_config

        timeout = global_config().gcs_rpc_timeout_s
        client = await self._raylet_client(address)
        try:
            return await client.call(
                method, payload, timeout=timeout if timeout > 0 else None)
        except asyncio.TimeoutError as e:
            raise GcsTimeoutError(method, address, timeout) from e

    # ---- object directory ----
    async def handle_add_object_location(self, payload, conn):
        self.object_locations.setdefault(payload["object_id"], set()).add(payload["node_id"])
        return True

    async def handle_add_object_locations(self, payload, conn):
        """Batched location adds (raylets coalesce seal reports — the
        directory write amortizes to one frame per flush window)."""
        node_id = payload["node_id"]
        for oid in payload["object_ids"]:
            self.object_locations.setdefault(oid, set()).add(node_id)
        return True

    async def handle_remove_object_location(self, payload, conn):
        """Drop one node's copy (evicted/freed/stale). The last copy vanishing
        via eviction is NOT a loss event — the object may be recreated; loss is
        declared only on node death (see _mark_node_dead)."""
        nodes = self.object_locations.get(payload["object_id"])
        if nodes is not None:
            nodes.discard(payload["node_id"])
            if not nodes:
                del self.object_locations[payload["object_id"]]
        return True

    async def handle_list_object_locations(self, payload, conn):
        return {oid: set(nodes)
                for oid, nodes in self.object_locations.items()}

    async def handle_get_object_locations(self, payload, conn):
        """oid -> [(node_id, raylet_address)] for live holders, plus a
        "__transfer__" side map {node_hex: transfer_address}. The holder
        tuples stay 2-wide on purpose: a pre-transfer-plane raylet
        unpacks `for node_id, address in ...` and a widened tuple would
        break ITS pulls, while an extra top-level key is invisible to
        it (wire-compat: additive only)."""
        out = {}
        transfer = {}
        for oid in payload["object_ids"]:
            holders = []
            for node_id in self.object_locations.get(oid, ()):
                info = self.nodes.get(node_id)
                if info is not None and info.alive:
                    holders.append((node_id, info.address))
                    if info.transfer_address:
                        transfer[node_id.hex()] = info.transfer_address
            out[oid] = holders
        out["__transfer__"] = transfer
        return out

    # ---- metrics (ref: stats/metric.h registry + metrics agent; the GCS
    #      is the aggregation point the state API reads) ----
    async def handle_report_metrics(self, payload, conn):
        worker = payload["worker_id"]
        for entry in payload["metrics"]:
            key = (entry["name"], tuple(sorted(entry["tags"].items())), worker)
            # bounded like task_events: worker churn + high-cardinality tags
            # must not grow the GCS without limit (FIFO eviction)
            if key not in self.metrics and len(self.metrics) >= self.MAX_METRICS:
                self.metrics.pop(next(iter(self.metrics)))
            self.metrics[key] = {
                "name": entry["name"], "kind": entry["kind"],
                "tags": entry["tags"], "value": entry["value"],
                "worker_id": worker,
                "description": entry.get("description", ""),
            }
        return True

    def _aggregate_metrics(self, name_filter=None) -> List[dict]:
        """Aggregated across workers: counters/histogram buckets sum,
        gauges report per-worker last values summed (the common scrape
        semantic for distributed gauges of additive quantities)."""
        out: Dict[tuple, dict] = {}
        for (name, tags, _worker), entry in self.metrics.items():
            if name_filter and name != name_filter:
                continue
            agg_key = (name, tags)
            if agg_key in out:
                out[agg_key]["value"] += entry["value"]
            else:
                out[agg_key] = dict(entry)
                out[agg_key].pop("worker_id", None)
        result = list(out.values())
        result.extend(self._process_metrics(name_filter))
        result.extend(self._train_metrics(name_filter))
        return result

    def _process_metrics(self, name_filter=None) -> List[dict]:
        """Synthetic per-process liveness series the GCS mints itself:
        process_uptime_seconds (head + every alive raylet, from
        registration time) and process_crashes_total (per node, with
        reason/signal labels, fed by the crash sweeps). They ride the
        normal aggregation so Prometheus, the series store and `cli
        status` all see them with no extra plumbing."""
        now = time.time()
        entries: List[dict] = []
        if not name_filter or name_filter == "process_uptime_seconds":
            entries.append({
                "name": "process_uptime_seconds", "kind": "gauge",
                "tags": {"role": "gcs", "node": "head"},
                "value": now - self.started_at,
                "description": "seconds since this process came up"})
            for info in self.nodes.values():
                if not info.alive:
                    continue
                first = self._node_first_seen.get(info.node_id.hex())
                if first is None:
                    continue
                entries.append({
                    "name": "process_uptime_seconds", "kind": "gauge",
                    "tags": {"role": "raylet",
                             "node": info.node_id.hex()[:12]},
                    "value": now - first,
                    "description": "seconds since this process came up"})
        if not name_filter or name_filter == "process_crashes_total":
            for (node, role, reason, sig), n in self.crash_counts.items():
                entries.append({
                    "name": "process_crashes_total", "kind": "counter",
                    "tags": {"node": node, "role": role,
                             "reason": reason, "signal": sig},
                    "value": float(n),
                    "description": "abnormal process exits (bundled)"})
        return entries

    async def handle_get_metrics(self, payload, conn):
        return self._aggregate_metrics(payload.get("name"))

    # ---- SLO observability plane (ray_tpu/slo.py; ROADMAP item 4's
    #      sensing layer: series retention -> quantiles -> burn alerts) ----
    async def _slo_loop(self):
        """Each tick: snapshot the aggregated metrics view into the
        per-series ring buffers, then evaluate every SLO spec against
        the fresh series (attainment + multi-window burn rates). Alert
        transitions land in the cluster-event log through _event, so
        `cli.py events`/`cli.py slo` and the dashboard see them with no
        extra plumbing."""
        from .config import global_config

        period = max(0.25, global_config().slo_eval_interval_s)
        last_err = None
        while True:
            await asyncio.sleep(period)
            try:
                now = time.time()
                self.series_store.sample(self._aggregate_metrics(), now)
                self.slo_monitor.tick(self.series_store, now,
                                      emit=self._slo_emit)
                last_err = None
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # surface once per distinct failure, not once per tick —
                # a persistent bug must not flood the event deque
                msg = f"{type(e).__name__}: {e}"
                if msg != last_err:
                    last_err = msg
                    self._event("slo", "ERROR",
                                f"SLO evaluation tick failed: {msg}")

    def _slo_emit(self, severity: str, message: str, **fields) -> None:
        """SLO alert-transition sink: the event lands in the stream as
        before, and a fast-burn ERROR additionally self-diagnoses —
        profile burst + stack sweep + memory report captured NOW, while
        the burn is live, with the artifact paths attached to the alert
        event (the on-call reads the page and the evidence together)."""
        self._event("slo", severity, message, **fields)
        if severity != "ERROR" or fields.get("kind") != "fast_burn":
            return
        now = time.time()
        if now - self._last_diag_t < 30.0:
            return  # one burst per page storm, not one per spec
        self._last_diag_t = now
        alert_rec = self.events[-1]  # the event just appended above
        background(self._self_diagnose(alert_rec))

    async def _self_diagnose(self, alert_rec: dict) -> None:
        """Capture the three forensic views and attach their paths to
        the triggering alert (mutating the deque'd record: later
        list_events readers see the artifacts on the alert itself)."""
        if not self.session_dir:
            return
        from . import blackbox

        out_dir = os.path.join(blackbox.incident_dir(self.session_dir),
                               str(int(time.time() * 1000)))
        artifacts: Dict[str, str] = {}

        async def _capture(name, coro):
            try:
                result = await coro
            except Exception as e:
                result = {"error": repr(e)}
            path = os.path.join(out_dir, f"{name}.json")
            try:
                os.makedirs(out_dir, exist_ok=True)
                with open(path, "w") as f:
                    json.dump(result, f, default=str)
                artifacts[name] = path
            except OSError:
                pass

        await _capture("profile", self.handle_profile_cluster(
            {"duration_s": 1.0, "hz": 50.0}, None))
        await _capture("stacks", self.handle_dump_all_stacks({}, None))
        await _capture("memory", self.handle_memory_report({}, None))
        alert_rec["artifacts"] = dict(artifacts)
        self._event("blackbox", "INFO",
                    f"self-diagnosis captured for '{alert_rec.get('slo')}'"
                    f" fast-burn: {', '.join(sorted(artifacts))}",
                    kind="self_diagnosis", slo=alert_rec.get("slo"),
                    artifacts=artifacts)

    async def handle_get_metric_series(self, payload, conn):
        """Ring-buffered samples for one metric (dashboard sparklines,
        loadgen reports). Selector is a tag-subset match."""
        if self.series_store is None:
            return []
        return self.series_store.query(
            payload["name"], payload.get("selector") or {})

    async def handle_slo_status(self, payload, conn):
        """Per-spec attainment/burn/alert records + the policy windows
        (so clients can render thresholds without re-reading config)."""
        if self.slo_monitor is None:
            return {"enabled": False, "specs": []}
        return {
            "enabled": True,
            "specs": self.slo_monitor.status(),
            "policies": [
                {"kind": p.kind, "severity": p.severity,
                 "short_window_s": p.short_window_s,
                 "long_window_s": p.long_window_s,
                 "threshold": p.threshold}
                for p in self.slo_monitor.policies],
        }

    async def handle_set_slo_specs(self, payload, conn):
        """Install/replace SLO specs at runtime (loadgen and tests use
        this; config slo_specs seeds the initial set). Malformed specs
        reject the whole batch — never half-install."""
        if self.slo_monitor is None:
            raise RuntimeError(
                "SLO monitor disabled (metrics_series_enabled=False or "
                "slo_eval_interval_s=0)")
        from ..slo import parse_specs

        specs = parse_specs(payload.get("specs") or [])
        self.slo_monitor.set_specs(specs)
        return [s.describe() for s in specs]

    # ---- training goodput plane (ray_tpu/train/telemetry.py ledger) ----
    def _train_ledger(self, job: str, world_size: int = 0):
        from ..train.telemetry import GoodputLedger
        from .config import global_config

        ledger = self.train_ledgers.get(job)
        if ledger is None:
            while len(self.train_ledgers) >= self.MAX_TRAIN_JOBS:
                self.train_ledgers.pop(next(iter(self.train_ledgers)))
            ledger = self.train_ledgers[job] = GoodputLedger(
                job, world_size=world_size or 1,
                peak_flops_per_chip=(
                    global_config().train_peak_flops_per_chip))
        if world_size:
            ledger.world_size = max(1, int(world_size))
        return ledger

    async def handle_train_report(self, payload, conn):
        """Fold a batch of per-rank TrainStepTelemetry records — or a
        controller restart notice — into the job's goodput ledger.
        Rank timestamps are clock-corrected here (NodeInfo.clock_offset,
        the collective-watchdog path), so straggler skew measured across
        hosts is real skew, not NTP noise."""
        job = str(payload.get("job") or "default")
        ledger = self._train_ledger(job,
                                    int(payload.get("world_size") or 0))
        if payload.get("kind") == "restart":
            restore_step = int(payload.get("restore_step") or 0)
            expected = ledger.restart(restore_step)
            self._event(
                "train", "WARNING",
                f"train job '{job}' gang restart #{ledger.restarts} from "
                f"checkpoint step {restore_step}: ~{expected} step(s) will "
                f"be re-executed (rework badput)",
                kind="train_restart", job=job, restore_step=restore_step,
                expected_rework=expected,
                failure=str(payload.get("failure") or "")[:500])
            return True
        from ..train.telemetry import TrainStepTelemetry

        for rec in payload.get("records") or []:
            if isinstance(rec, dict):       # tolerate dict-shaped reports
                rec = TrainStepTelemetry(**{
                    k: v for k, v in rec.items()
                    if k in TrainStepTelemetry.__dataclass_fields__})
            if not isinstance(rec, TrainStepTelemetry):
                continue
            rec.start_t = self._corrected_time(rec.node_id, rec.start_t)
            rec.end_t = self._corrected_time(rec.node_id, rec.end_t)
            ledger.add(rec)
        return True

    async def handle_train_status(self, payload, conn):
        """Per-job goodput snapshots (TrainJobLedger records) for
        `cli train`, the dashboard Train panel and state.train_status()."""
        job = payload.get("job")
        ledgers = ([self.train_ledgers[job]]
                   if job and job in self.train_ledgers
                   else list(self.train_ledgers.values()))
        return {"jobs": [ledger.to_record() for ledger in ledgers]}

    def _train_metrics(self, name_filter=None) -> List[dict]:
        """Synthetic per-job goodput series minted from the ledgers:
        they ride the normal aggregation, so Prometheus, the SeriesStore
        and the SLO engine (mfu floor specs, burn-rate alerts) see them
        with no extra plumbing."""
        entries: List[dict] = []

        def want(name):
            return not name_filter or name_filter == name

        for job, ledger in self.train_ledgers.items():
            tags = {"job": job}
            goodput = ledger.goodput_fraction()
            if want("train_goodput_fraction") and goodput is not None:
                entries.append({
                    "name": "train_goodput_fraction", "kind": "gauge",
                    "tags": tags, "value": goodput,
                    "description": "productive / total attributed "
                                   "chip-seconds"})
            if want("train_mfu") and ledger.mfu > 0.0:
                entries.append({
                    "name": "train_mfu", "kind": "gauge", "tags": tags,
                    "value": ledger.mfu,
                    "description": "model flops utilization (EMA over "
                                   "recent steps)"})
            if (want("train_tokens_per_s_per_chip")
                    and ledger.tok_per_s_per_chip > 0.0):
                entries.append({
                    "name": "train_tokens_per_s_per_chip", "kind": "gauge",
                    "tags": tags, "value": ledger.tok_per_s_per_chip,
                    "description": "training throughput per chip (EMA)"})
            if want("train_badput_seconds_total"):
                for cause, secs in sorted(ledger.badput_s.items()):
                    entries.append({
                        "name": "train_badput_seconds_total",
                        "kind": "counter",
                        "tags": {"job": job, "cause": cause},
                        "value": secs,
                        "description": "non-productive chip-seconds by "
                                       "cause (MegaScale taxonomy)"})
            if want("train_rework_steps_total") and ledger.rework_steps:
                entries.append({
                    "name": "train_rework_steps_total", "kind": "counter",
                    "tags": tags, "value": float(ledger.rework_steps),
                    "description": "steps re-executed after checkpoint "
                                   "restores"})
            if want("train_compile_total"):
                for kind, n in (("cold", ledger.compile_count),
                                ("cache_hit", ledger.cache_hit_count)):
                    if n:
                        entries.append({
                            "name": "train_compile_total",
                            "kind": "counter",
                            "tags": {"job": job, "kind": kind},
                            "value": float(n),
                            "description": "step-fn compiles by kind"})
        return entries

    # ---- task events (ref: gcs_task_manager.h — the state API backend) ----
    _TERMINAL_STATES = ("FINISHED", "FAILED")

    def _evict_task_event(self) -> None:
        """Make room for one record: prefer the oldest TERMINAL record —
        evicting a still-RUNNING task's record would lose live state the
        moment the table fills with completed history."""
        victim = None
        for key, rec in self.task_events.items():
            if rec.get("state") in self._TERMINAL_STATES:
                victim = key
                break
        if victim is None:
            victim = next(iter(self.task_events))
        self.task_events.pop(victim)

    async def handle_report_task_events(self, payload, conn):
        for event in payload["events"]:
            task_id = event["task_id"]
            record = self.task_events.get(task_id)
            if record is None:
                if len(self.task_events) >= self.MAX_TASK_EVENTS:
                    self._evict_task_event()
                record = self.task_events[task_id] = {
                    "task_id": task_id, "name": "", "state": "",
                    "start_time": None, "end_time": None, "error": "",
                    "state_transitions": [],
                }
            # lifecycle transitions accumulate (append-merge); every
            # other field is last-writer-wins as before
            transitions = event.get("transitions")
            record.update({k: v for k, v in event.items()
                           if v is not None and k != "transitions"})
            if transitions:
                record.setdefault("state_transitions",
                                  []).extend(transitions)
        return True

    async def handle_list_task_events(self, payload, conn):
        return list(self.task_events.values())

    # ---- health / introspection ----
    async def handle_ping(self, payload, conn):
        return {"time": time.time()}

    async def handle_report_clock_offset(self, payload, conn):
        """Store a node's smoothed clock offset (raylet clock-sync loop;
        NTP-style offset = GCS time - node-local midpoint)."""
        node_id = payload["node_id"]
        if isinstance(node_id, str):
            node_id = NodeID.from_hex(node_id)
        info = self.nodes.get(node_id)
        if info is None:
            return False
        info.clock_offset = float(payload["offset"])
        return True

    async def handle_cluster_status(self, payload, conn):
        return {
            "nodes": list(self.nodes.values()),
            "num_actors": len(self.actors),
            "num_jobs": len(self.jobs),
        }
