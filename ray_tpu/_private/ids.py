"""Binary unique identifiers for jobs/tasks/actors/objects/nodes.

TPU-native re-design of the reference ID scheme (ref: src/ray/common/id.h —
JobID/TaskID/ActorID/ObjectID/NodeID with lineage-encoded bits). We keep the
same structural idea: ObjectIDs embed the TaskID that created them plus a
return-index, TaskIDs embed the ActorID/JobID, so ownership and lineage can be
derived from an ID without a directory lookup.
"""

from __future__ import annotations

import os
import threading

_rng_lock = threading.Lock()
# Uniqueness, not cryptography: a 4 KiB os.urandom buffer drained from
# the tail amortizes one syscall over ~hundreds of ids (3+ ids minted
# per submit on the hot path). Refilled on exhaustion or fork (pid
# check) so children diverge.
_rng_state = {"pid": None, "buf": bytearray()}


def _random_bytes(n: int) -> bytes:
    """Buffered randomness: ids are minted on every submit (3+ per task),
    so amortize one urandom read over ~hundreds of ids instead of taking
    the RNG through getrandbits per id. Fork-safe via the pid check."""
    pid = os.getpid()
    with _rng_lock:
        if _rng_state["pid"] != pid or len(_rng_state["buf"]) < n:
            _rng_state["pid"] = pid
            _rng_state["buf"] = bytearray(os.urandom(4096))
        buf = _rng_state["buf"]
        out = bytes(buf[-n:])
        del buf[-n:]
        return out


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes", "_h")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)
        # ids key every hot-path dict; hash once, not per lookup
        self._h = hash((type(self).__name__, self._bytes))

    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(4, "little"))

    def to_int(self) -> int:
        return int.from_bytes(self._bytes, "little")


class NodeID(BaseID):
    SIZE = 28


class WorkerID(BaseID):
    SIZE = 28


class ActorID(BaseID):
    """12 random bytes + 4-byte JobID."""

    SIZE = 16
    UNIQUE_BYTES = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_random_bytes(cls.UNIQUE_BYTES) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.UNIQUE_BYTES :])


class TaskID(BaseID):
    """8 random bytes + 16-byte ActorID (nil actor for normal tasks)."""

    SIZE = 24
    UNIQUE_BYTES = 8

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(_random_bytes(cls.UNIQUE_BYTES) + ActorID.of(job_id).binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_random_bytes(cls.UNIQUE_BYTES) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        nil_actor = b"\x00" * (ActorID.UNIQUE_BYTES - 4) + job_id.binary() + b"\x00" * 0
        # driver task: zero unique bytes + pseudo actor carrying the job id
        return cls(b"\x00" * cls.UNIQUE_BYTES + nil_actor[: ActorID.UNIQUE_BYTES] + job_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[self.UNIQUE_BYTES :])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    """24-byte TaskID + 4-byte little-endian return index.

    Lineage-encoded like the reference (src/ray/common/id.h): the creating task
    is recoverable from the object id, which is what makes lineage
    reconstruction possible without extra metadata.
    """

    SIZE = 28
    INDEX_BYTES = 4

    @classmethod
    def for_return(cls, task_id: TaskID, return_index: int) -> "ObjectID":
        return cls(task_id.binary() + return_index.to_bytes(cls.INDEX_BYTES, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # put objects use the high bit of the index to distinguish from returns
        idx = put_index | 0x80000000
        return cls(task_id.binary() + idx.to_bytes(cls.INDEX_BYTES, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE :], "little") & 0x7FFFFFFF

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bytes[TaskID.SIZE :], "little") & 0x80000000)

    def job_id(self) -> JobID:
        return self.task_id().job_id()


class PlacementGroupID(BaseID):
    SIZE = 18

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(_random_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.SIZE - JobID.SIZE :])
