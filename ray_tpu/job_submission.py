"""Job submission: run an entrypoint command on the cluster under a
supervisor actor (ref: python/ray/dashboard/modules/job/ —
JobSubmissionClient sdk.py:35, submit_job:125, job supervisor/manager;
the REST head is replaced by direct GCS-backed state + a detached
supervisor actor, which fits the socket-RPC control plane).

Status lives in the GCS KV (ns "jobs"), so any driver on the cluster can
list/poll jobs regardless of which driver submitted them and whether the
submitter is still alive (supervisors are detached).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

_NS = "jobs"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    status: str
    entrypoint: str
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0

    @classmethod
    def from_json(cls, raw: bytes) -> "JobInfo":
        return cls(**json.loads(raw))

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()


class _JobSupervisor:
    """Detached actor owning one job subprocess (ref: job supervisor
    actor in dashboard/modules/job/job_manager.py)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Optional[Dict[str, str]] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.env_vars = env_vars or {}
        self.log_path = os.path.join(
            "/tmp/ray_tpu_jobs", f"{submission_id}.log")
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        self._proc: Optional[subprocess.Popen] = None
        self._thread: Optional[threading.Thread] = None
        self._status = JobStatus.PENDING
        self._message = ""
        self._start = 0.0
        self._end = 0.0

    def _put_status(self) -> None:
        from . import _worker_api

        info = JobInfo(self.submission_id, self._status, self.entrypoint,
                       self._message, self._start, self._end)
        core = _worker_api.core()
        core.io.run(core.gcs.call("kv_put", {
            "ns": _NS, "key": self.submission_id, "value": info.to_json()}))

    def start(self) -> bool:
        env = dict(os.environ)
        env.update(self.env_vars)
        # the job's driver joins THIS cluster
        from . import _worker_api

        core = _worker_api.core()
        env["RAY_TPU_ADDRESS"] = core.gcs.address
        self._start = time.time()
        self._status = JobStatus.RUNNING
        self._put_status()
        log = open(self.log_path, "wb")
        self._proc = subprocess.Popen(
            self.entrypoint, shell=True, stdout=log, stderr=log, env=env,
            start_new_session=True)

        def _wait():
            rc = self._proc.wait()
            log.close()
            self._end = time.time()
            if self._status != JobStatus.STOPPED:
                self._status = (JobStatus.SUCCEEDED if rc == 0
                                else JobStatus.FAILED)
                self._message = f"exit code {rc}"
            self._put_status()

        # reaper: exits when the child it waits on dies — stop() releases
        # it by killing the process group, not by touching the thread
        self._thread = threading.Thread(  # graftlint: ignore[cleanup]
            target=_wait, daemon=True)
        self._thread.start()
        return True

    def stop(self) -> bool:
        if self._proc is not None and self._proc.poll() is None:
            self._status = JobStatus.STOPPED
            self._message = "stopped by user"
            try:
                os.killpg(os.getpgid(self._proc.pid), 15)
            except ProcessLookupError:
                pass
        return True

    def logs(self, tail_bytes: int = 1 << 20) -> bytes:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read()
        except FileNotFoundError:
            return b""

    def read_from(self, offset: int, limit: int = 1 << 20) -> bytes:
        """Absolute-offset read (log followers track a file offset, so
        output beyond any tail window is never dropped or garbled)."""
        try:
            with open(self.log_path, "rb") as f:
                f.seek(offset)
                return f.read(limit)
        except FileNotFoundError:
            return b""

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Submit/inspect jobs (ref: sdk.py:35 JobSubmissionClient). The
    ``address`` is the cluster GCS address; constructing the client
    attaches this process as a driver if it isn't one already."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address or
                         os.environ.get("RAY_TPU_ADDRESS"))

    def _kv(self, method: str, payload: dict):
        from . import _worker_api

        core = _worker_api.core()
        return core.io.run(core.gcs.call(method, payload))

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None) -> str:
        import ray_tpu

        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if self._kv("kv_get", {"ns": _NS, "key": submission_id}) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        unsupported = set(runtime_env or {}) - {"env_vars"}
        if unsupported:
            # silently running without the requested working_dir/modules
            # would fail far from the cause; tasks/actors support the
            # full runtime_env — the job subprocess supports env_vars
            raise ValueError(
                f"job runtime_env supports only 'env_vars' "
                f"(got {sorted(unsupported)}); use task/actor "
                f"runtime_env inside the job for working_dir/py_modules")
        env_vars = (runtime_env or {}).get("env_vars") or {}
        info = JobInfo(submission_id, JobStatus.PENDING, entrypoint)
        self._kv("kv_put", {"ns": _NS, "key": submission_id,
                            "value": info.to_json()})
        supervisor = ray_tpu.remote(_JobSupervisor).options(
            name=f"_job_supervisor:{submission_id}",
            lifetime="detached", num_cpus=0.1,
        ).remote(submission_id, entrypoint, env_vars)
        ray_tpu.get(supervisor.start.remote(), timeout=60)
        return submission_id

    def _supervisor(self, submission_id: str):
        import ray_tpu

        return ray_tpu.get_actor(f"_job_supervisor:{submission_id}")

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id).status

    def get_job_info(self, submission_id: str) -> JobInfo:
        raw = self._kv("kv_get", {"ns": _NS, "key": submission_id})
        if raw is None:
            raise ValueError(f"no such job {submission_id!r}")
        return JobInfo.from_json(raw)

    def list_jobs(self) -> List[JobInfo]:
        keys = self._kv("kv_keys", {"ns": _NS}) or []
        out = []
        for key in keys:
            raw = self._kv("kv_get", {"ns": _NS, "key": key})
            if raw:
                out.append(JobInfo.from_json(raw))
        return sorted(out, key=lambda j: j.start_time)

    def get_job_logs(self, submission_id: str) -> str:
        import ray_tpu

        sup = self._supervisor(submission_id)
        return ray_tpu.get(sup.logs.remote(), timeout=60).decode(
            errors="replace")

    def stop_job(self, submission_id: str) -> bool:
        import ray_tpu

        sup = self._supervisor(submission_id)
        return ray_tpu.get(sup.stop.remote(), timeout=60)

    def tail_job_logs(self, submission_id: str, *, poll_s: float = 0.5):
        """Generator yielding log increments until the job terminates.
        Follows an absolute file offset, so logs larger than any tail
        window stream completely."""
        import codecs

        import ray_tpu

        sup = self._supervisor(submission_id)
        offset = 0
        # incremental decoder: a multibyte char split at a read boundary
        # must not decode as replacement characters
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")

        def _drain():
            nonlocal offset
            while True:
                chunk = ray_tpu.get(sup.read_from.remote(offset),
                                    timeout=60)
                if not chunk:
                    return
                offset += len(chunk)
                text = decoder.decode(chunk)
                if text:
                    yield text

        while True:
            yield from _drain()
            if self.get_job_status(submission_id) in JobStatus.TERMINAL:
                yield from _drain()
                return
            time.sleep(poll_s)
