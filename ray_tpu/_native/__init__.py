"""Native (C++) substrate loader: builds and binds libray_tpu_core.so
(ref: SURVEY §2.1 — native components get C++ equivalents, not Python
stand-ins; this module is the N17 Python⇄native bridge for them).

Sources under native/ (store_index.cc: shared store index; fastlane.cc:
shm task-submission rings; core_tables.cc: refcount table + lease
scheduler) compile on demand with g++ into ray_tpu/_native/build/. The
cache key is a CONTENT HASH of all sources baked into the output
filename — a stale binary can never shadow edited sources, and builds
race safely via atomic rename. Loading failures degrade gracefully —
callers fall back to pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")
_BUILD = os.path.join(_HERE, "build")
_LOCK = threading.Lock()
_LIB = None
_LIB_ERR: Optional[str] = None

ID_LEN = 28

_SOURCES = ("store_index.cc", "fastlane.cc", "core_tables.cc")


def _build_lib() -> str:
    srcs = [os.path.join(_SRC, s) for s in _SOURCES]
    # sanitizer build mode (ref: the reference's .bazelrc tsan/asan
    # configs): RAY_TPU_NATIVE_SANITIZE=address|thread recompiles the
    # native libs instrumented; ci.sh --sanitize wires the LD_PRELOAD
    extra = []
    san = os.environ.get("RAY_TPU_NATIVE_SANITIZE", "")
    if san:
        extra = [f"-fsanitize={san}", "-fno-omit-frame-pointer", "-g"]
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(san.encode())  # sanitized builds cache separately
    # variant tag in the name: the sweep below must only reap builds of
    # the SAME variant — a sanitize run deleting the normal build would
    # drop concurrent normal processes onto the pure-Python fallback
    variant = f"libray_tpu_core_{san or 'std'}"
    out = os.path.join(_BUILD, f"{variant}_{h.hexdigest()[:16]}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_BUILD, exist_ok=True)
    tmp = out + f".tmp.{os.getpid()}"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", *extra,
         "-o", tmp, *srcs, "-lpthread"],
        check=True, capture_output=True, timeout=180)
    os.replace(tmp, out)  # atomic: concurrent builders race safely
    # sweep superseded builds of this variant only (best effort)
    for f in os.listdir(_BUILD):
        if f.startswith(variant) and f.endswith(".so") \
                and os.path.join(_BUILD, f) != out:
            try:
                os.unlink(os.path.join(_BUILD, f))
            except OSError:
                pass
    return out


def get_lib():
    """The loaded native library, or None (with the reason recorded)."""
    global _LIB, _LIB_ERR
    with _LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        if os.environ.get("RAY_TPU_NATIVE_STORE", "1") == "0":
            _LIB_ERR = "disabled via RAY_TPU_NATIVE_STORE=0"
            return None
        try:
            lib = ctypes.CDLL(_build_lib())
        except Exception as e:  # no g++ / bad toolchain: pure-Python path
            _LIB_ERR = repr(e)
            return None
        # ---- store index ----
        lib.rtpu_idx_open.restype = ctypes.c_void_p
        lib.rtpu_idx_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64, ctypes.c_char_p]
        lib.rtpu_idx_close.argtypes = [ctypes.c_void_p]
        lib.rtpu_idx_reserve.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.rtpu_idx_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_idx_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_idx_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_uint64),
                                        ctypes.c_int]
        lib.rtpu_idx_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
        lib.rtpu_idx_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_idx_set_spill_dir.restype = None
        lib.rtpu_idx_set_spill_dir.argtypes = [ctypes.c_void_p,
                                               ctypes.c_char_p]
        for fn in ("rtpu_idx_used", "rtpu_idx_live", "rtpu_idx_capacity"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.rtpu_fence.restype = None
        lib.rtpu_fence.argtypes = []
        # ---- fastlane rings ----
        lib.rtpu_ring_create.restype = ctypes.c_void_p
        lib.rtpu_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        lib.rtpu_ring_open.restype = ctypes.c_void_p
        lib.rtpu_ring_open.argtypes = [ctypes.c_char_p]
        lib.rtpu_ring_push.restype = ctypes.c_int
        lib.rtpu_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint32, ctypes.c_int]
        lib.rtpu_ring_pop.restype = ctypes.c_int64
        lib.rtpu_ring_pop.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int]
        lib.rtpu_ring_close.argtypes = [ctypes.c_void_p]
        lib.rtpu_ring_closed.restype = ctypes.c_int
        lib.rtpu_ring_closed.argtypes = [ctypes.c_void_p]
        lib.rtpu_ring_free.argtypes = [ctypes.c_void_p]
        # ---- refcount table ----
        lib.rtpu_rc_open.restype = ctypes.c_void_p
        lib.rtpu_rc_open.argtypes = []
        lib.rtpu_rc_close.argtypes = [ctypes.c_void_p]
        for fn in ("rtpu_rc_add_local", "rtpu_rc_pin_dep",
                   "rtpu_rc_set_borrowed"):
            getattr(lib, fn).restype = None
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        for fn in ("rtpu_rc_remove_local", "rtpu_rc_unpin_dep",
                   "rtpu_rc_contains", "rtpu_rc_local_count"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_rc_size.restype = ctypes.c_uint64
        lib.rtpu_rc_size.argtypes = [ctypes.c_void_p]
        # ---- lease scheduler ----
        U32P = ctypes.POINTER(ctypes.c_uint32)
        F64P = ctypes.POINTER(ctypes.c_double)
        U64P = ctypes.POINTER(ctypes.c_uint64)
        lib.rtpu_sched_open.restype = ctypes.c_void_p
        lib.rtpu_sched_open.argtypes = [ctypes.c_uint64]
        lib.rtpu_sched_close.argtypes = [ctypes.c_void_p]
        lib.rtpu_sched_node_upsert.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, U32P, F64P, F64P,
            ctypes.c_uint32]
        lib.rtpu_sched_node_remove.argtypes = [ctypes.c_void_p,
                                               ctypes.c_uint64]
        lib.rtpu_sched_try_allocate.restype = ctypes.c_int
        lib.rtpu_sched_try_allocate.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, U32P, F64P, ctypes.c_uint32]
        lib.rtpu_sched_release.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, U32P, F64P, ctypes.c_uint32]
        lib.rtpu_sched_queue_push.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, U32P, F64P, ctypes.c_uint32,
            ctypes.c_int32, ctypes.c_uint64]
        lib.rtpu_sched_queue_remove.restype = ctypes.c_int
        lib.rtpu_sched_queue_remove.argtypes = [ctypes.c_void_p,
                                                ctypes.c_uint64]
        lib.rtpu_sched_pending.restype = ctypes.c_uint64
        lib.rtpu_sched_pending.argtypes = [ctypes.c_void_p]
        lib.rtpu_sched_pump.restype = ctypes.c_uint64
        lib.rtpu_sched_pump.argtypes = [ctypes.c_void_p, U64P, U64P,
                                        ctypes.c_uint64]
        lib.rtpu_sched_avail.restype = ctypes.c_double
        lib.rtpu_sched_avail.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                         ctypes.c_uint32]
        _LIB = lib
        return _LIB


def native_unavailable_reason() -> Optional[str]:
    get_lib()
    return _LIB_ERR


class NativeIndex:
    """ctypes handle over the shared store index (one per store dir)."""

    MAX_VICTIMS = 4096

    def __init__(self, path: str, capacity: int, nslots: int = 1 << 16,
                 data_dir: Optional[str] = None):
        """``data_dir``: directory of per-object data files (hex names);
        when given, eviction unlinks victims' files under the index
        mutex, closing the evict-vs-recreate race."""
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native store unavailable: {_LIB_ERR}")
        self._lib = lib
        self._h = lib.rtpu_idx_open(
            path.encode(), capacity, nslots,
            data_dir.encode() if data_dir else None)
        if not self._h:
            raise RuntimeError(f"cannot open native index at {path}")
        self._victims = ctypes.create_string_buffer(
            ID_LEN * self.MAX_VICTIMS)

    def reserve(self, oid: bytes, size: int) -> Tuple[int, List[bytes]]:
        """(rc, evicted_ids): rc 0 ok, -1 impossible, -2 exists,
        -3 table full. Caller unlinks the evicted ids' data files."""
        n = ctypes.c_uint32(0)
        rc = self._lib.rtpu_idx_reserve(
            self._h, oid, size, self._victims, self.MAX_VICTIMS,
            ctypes.byref(n))
        raw = self._victims.raw
        victims = [raw[i * ID_LEN:(i + 1) * ID_LEN]
                   for i in range(n.value)]
        return rc, victims

    def set_spill_dir(self, path: str) -> None:
        self._lib.rtpu_idx_set_spill_dir(self._h, path.encode())

    def seal(self, oid: bytes) -> int:
        return self._lib.rtpu_idx_seal(self._h, oid)

    def abort(self, oid: bytes) -> int:
        return self._lib.rtpu_idx_abort(self._h, oid)

    def lookup(self, oid: bytes, touch: bool = True) -> Tuple[int, int]:
        """(state, size): state 0 sealed, 1 absent, 2 creating.
        ``touch=False`` for existence probes (no LRU refresh)."""
        size = ctypes.c_uint64(0)
        rc = self._lib.rtpu_idx_lookup(self._h, oid, ctypes.byref(size),
                                       1 if touch else 0)
        return rc, size.value

    def pin(self, oid: bytes) -> None:
        self._lib.rtpu_idx_pin(self._h, oid, 1)

    def unpin(self, oid: bytes) -> None:
        self._lib.rtpu_idx_pin(self._h, oid, -1)

    def delete(self, oid: bytes) -> int:
        return self._lib.rtpu_idx_delete(self._h, oid)

    def used(self) -> int:
        return self._lib.rtpu_idx_used(self._h)

    def live(self) -> int:
        return self._lib.rtpu_idx_live(self._h)

    def capacity(self) -> int:
        return self._lib.rtpu_idx_capacity(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_idx_close(self._h)
            self._h = None


class Ring:
    """SPSC-ish shm byte ring with futex wakeups (native/fastlane.cc).

    ``push``/``pop`` release the GIL (ctypes) — safe to block on from
    dedicated threads. Records are bytes; framing is the caller's."""

    def __init__(self, path: str, capacity: int = 1 << 20, *,
                 create: bool = False):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native lib unavailable: {_LIB_ERR}")
        self._lib = lib
        self.path = path
        if create:
            self._h = lib.rtpu_ring_create(path.encode(), capacity)
        else:
            self._h = lib.rtpu_ring_open(path.encode())
        if not self._h:
            raise RuntimeError(f"cannot open ring at {path}")
        self._buf = ctypes.create_string_buffer(1 << 16)

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        """False on timeout; raises when the ring is closed."""
        rc = self._lib.rtpu_ring_push(self._h, data, len(data), timeout_ms)
        if rc == 0:
            return True
        if rc == -2:
            return False
        if rc == -1:
            raise BrokenPipeError(f"ring closed: {self.path}")
        raise ValueError(f"ring push rc={rc} (len={len(data)})")

    def pop(self, timeout_ms: int = -1) -> Optional[bytes]:
        """None on timeout; raises BrokenPipeError when closed+drained."""
        need = ctypes.c_uint32(0)
        while True:
            n = self._lib.rtpu_ring_pop(
                self._h, self._buf, len(self._buf), ctypes.byref(need),
                timeout_ms)
            if n >= 0:
                return self._buf.raw[:n]
            if n == -2:
                return None
            if n == -1:
                raise BrokenPipeError(f"ring closed: {self.path}")
            if n == -3:  # grow and retry
                self._buf = ctypes.create_string_buffer(
                    max(need.value, len(self._buf) * 2))
                continue
            raise ValueError(f"ring pop rc={n}")

    def close_write(self) -> None:
        if self._h:
            self._lib.rtpu_ring_close(self._h)

    @property
    def closed(self) -> bool:
        return bool(self._lib.rtpu_ring_closed(self._h)) if self._h else True

    def free(self) -> None:
        if self._h:
            self._lib.rtpu_ring_free(self._h)
            self._h = None

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class RefTable:
    """Native distributed-refcount table (core_tables.cc; ref:
    reference_count.h:66). Free decisions: 0 keep, 1 free (owned),
    2 drop local state only (borrowed)."""

    def __init__(self):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native lib unavailable: {_LIB_ERR}")
        self._lib = lib
        self._h = lib.rtpu_rc_open()

    def add_local(self, oid: bytes) -> None:
        self._lib.rtpu_rc_add_local(self._h, oid)

    def remove_local(self, oid: bytes) -> int:
        return self._lib.rtpu_rc_remove_local(self._h, oid)

    def pin_dep(self, oid: bytes) -> None:
        self._lib.rtpu_rc_pin_dep(self._h, oid)

    def unpin_dep(self, oid: bytes) -> int:
        return self._lib.rtpu_rc_unpin_dep(self._h, oid)

    def set_borrowed(self, oid: bytes) -> None:
        self._lib.rtpu_rc_set_borrowed(self._h, oid)

    def contains(self, oid: bytes) -> bool:
        return bool(self._lib.rtpu_rc_contains(self._h, oid))

    def local_count(self, oid: bytes) -> int:
        return self._lib.rtpu_rc_local_count(self._h, oid)

    def __len__(self) -> int:
        return self._lib.rtpu_rc_size(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_rc_close(self._h)
            self._h = None


class LeaseScheduler:
    """Native lease queue + dispatch engine (core_tables.cc; ref:
    cluster_task_manager.h + hybrid_scheduling_policy.h:50).

    Resource names are interned to u32 ids per instance; node ids are
    u64 handles chosen by the caller. ``pump`` sweeps the whole backlog
    natively and returns [(req_id, node_handle)] grants."""

    SPREAD = 1
    NO_SPILL = 2

    def __init__(self, local_node: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native lib unavailable: {_LIB_ERR}")
        self._lib = lib
        self._h = lib.rtpu_sched_open(local_node)
        self._intern: dict = {}
        self._out_req = (ctypes.c_uint64 * 4096)()
        self._out_node = (ctypes.c_uint64 * 4096)()

    def _vec(self, resources: dict):
        n = len(resources)
        ids = (ctypes.c_uint32 * n)()
        vals = (ctypes.c_double * n)()
        for i, (k, v) in enumerate(resources.items()):
            rid = self._intern.get(k)
            if rid is None:
                rid = self._intern[k] = len(self._intern) + 1
            ids[i] = rid
            vals[i] = float(v)
        return ids, vals, n

    def node_upsert(self, node: int, total: dict, available: dict) -> None:
        keys = sorted(set(total) | set(available))
        merged_tot = {k: total.get(k, 0.0) for k in keys}
        ids, tot, n = self._vec(merged_tot)
        av = (ctypes.c_double * n)()
        for i, k in enumerate(merged_tot):
            av[i] = float(available.get(k, 0.0))
        self._lib.rtpu_sched_node_upsert(self._h, node, ids, tot, av, n)

    def node_remove(self, node: int) -> None:
        self._lib.rtpu_sched_node_remove(self._h, node)

    def try_allocate(self, node: int, resources: dict) -> bool:
        ids, vals, n = self._vec(resources)
        return bool(self._lib.rtpu_sched_try_allocate(
            self._h, node, ids, vals, n))

    def release(self, node: int, resources: dict) -> None:
        ids, vals, n = self._vec(resources)
        self._lib.rtpu_sched_release(self._h, node, ids, vals, n)

    def queue_push(self, req_id: int, resources: dict, *,
                   spread: bool = False, no_spill: bool = False,
                   affinity_node: int = 0) -> None:
        ids, vals, n = self._vec(resources)
        flags = (self.SPREAD if spread else 0) | \
            (self.NO_SPILL if no_spill else 0)
        self._lib.rtpu_sched_queue_push(self._h, req_id, ids, vals, n,
                                        flags, affinity_node)

    def queue_remove(self, req_id: int) -> bool:
        return bool(self._lib.rtpu_sched_queue_remove(self._h, req_id))

    def pending(self) -> int:
        return self._lib.rtpu_sched_pending(self._h)

    def pump(self) -> List[Tuple[int, int]]:
        n = self._lib.rtpu_sched_pump(self._h, self._out_req,
                                      self._out_node, 4096)
        return [(self._out_req[i], self._out_node[i]) for i in range(n)]

    def avail(self, node: int, resource: str) -> float:
        rid = self._intern.get(resource)
        if rid is None:
            return 0.0
        return self._lib.rtpu_sched_avail(self._h, node, rid)

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_sched_close(self._h)
            self._h = None
