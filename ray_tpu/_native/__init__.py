"""Native (C++) substrate loader: builds and binds libray_tpu_store.so
(ref: SURVEY §2.1 — native components get C++ equivalents, not Python
stand-ins; this module is the N17 Python⇄native bridge for them).

The library is compiled on demand with g++ into ray_tpu/_native/build/
(cached by source mtime); loading failures degrade gracefully — callers
fall back to pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")
_BUILD = os.path.join(_HERE, "build")
_LOCK = threading.Lock()
_LIB = None
_LIB_ERR: Optional[str] = None

ID_LEN = 28


def _build_lib() -> str:
    src = os.path.join(_SRC, "store_index.cc")
    out = os.path.join(_BUILD, "libray_tpu_store.so")
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    os.makedirs(_BUILD, exist_ok=True)
    tmp = out + f".tmp.{os.getpid()}"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src,
         "-lpthread"],
        check=True, capture_output=True, timeout=120)
    os.replace(tmp, out)  # atomic: concurrent builders race safely
    return out


def get_lib():
    """The loaded native library, or None (with the reason recorded)."""
    global _LIB, _LIB_ERR
    with _LOCK:
        if _LIB is not None or _LIB_ERR is not None:
            return _LIB
        if os.environ.get("RAY_TPU_NATIVE_STORE", "1") == "0":
            _LIB_ERR = "disabled via RAY_TPU_NATIVE_STORE=0"
            return None
        try:
            lib = ctypes.CDLL(_build_lib())
        except Exception as e:  # no g++ / bad toolchain: pure-Python path
            _LIB_ERR = repr(e)
            return None
        lib.rtpu_idx_open.restype = ctypes.c_void_p
        lib.rtpu_idx_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_uint64, ctypes.c_char_p]
        lib.rtpu_idx_close.argtypes = [ctypes.c_void_p]
        lib.rtpu_idx_reserve.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32)]
        lib.rtpu_idx_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_idx_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_idx_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_uint64),
                                        ctypes.c_int]
        lib.rtpu_idx_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int]
        lib.rtpu_idx_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        for fn in ("rtpu_idx_used", "rtpu_idx_live", "rtpu_idx_capacity"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def native_unavailable_reason() -> Optional[str]:
    get_lib()
    return _LIB_ERR


class NativeIndex:
    """ctypes handle over the shared store index (one per store dir)."""

    MAX_VICTIMS = 4096

    def __init__(self, path: str, capacity: int, nslots: int = 1 << 16,
                 data_dir: Optional[str] = None):
        """``data_dir``: directory of per-object data files (hex names);
        when given, eviction unlinks victims' files under the index
        mutex, closing the evict-vs-recreate race."""
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native store unavailable: {_LIB_ERR}")
        self._lib = lib
        self._h = lib.rtpu_idx_open(
            path.encode(), capacity, nslots,
            data_dir.encode() if data_dir else None)
        if not self._h:
            raise RuntimeError(f"cannot open native index at {path}")
        self._victims = ctypes.create_string_buffer(
            ID_LEN * self.MAX_VICTIMS)

    def reserve(self, oid: bytes, size: int) -> Tuple[int, List[bytes]]:
        """(rc, evicted_ids): rc 0 ok, -1 impossible, -2 exists,
        -3 table full. Caller unlinks the evicted ids' data files."""
        n = ctypes.c_uint32(0)
        rc = self._lib.rtpu_idx_reserve(
            self._h, oid, size, self._victims, self.MAX_VICTIMS,
            ctypes.byref(n))
        raw = self._victims.raw
        victims = [raw[i * ID_LEN:(i + 1) * ID_LEN]
                   for i in range(n.value)]
        return rc, victims

    def seal(self, oid: bytes) -> int:
        return self._lib.rtpu_idx_seal(self._h, oid)

    def abort(self, oid: bytes) -> int:
        return self._lib.rtpu_idx_abort(self._h, oid)

    def lookup(self, oid: bytes, touch: bool = True) -> Tuple[int, int]:
        """(state, size): state 0 sealed, 1 absent, 2 creating.
        ``touch=False`` for existence probes (no LRU refresh)."""
        size = ctypes.c_uint64(0)
        rc = self._lib.rtpu_idx_lookup(self._h, oid, ctypes.byref(size),
                                       1 if touch else 0)
        return rc, size.value

    def pin(self, oid: bytes) -> None:
        self._lib.rtpu_idx_pin(self._h, oid, 1)

    def unpin(self, oid: bytes) -> None:
        self._lib.rtpu_idx_pin(self._h, oid, -1)

    def delete(self, oid: bytes) -> int:
        return self._lib.rtpu_idx_delete(self._h, oid)

    def used(self) -> int:
        return self._lib.rtpu_idx_used(self._h)

    def live(self) -> int:
        return self._lib.rtpu_idx_live(self._h)

    def capacity(self) -> int:
        return self._lib.rtpu_idx_capacity(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.rtpu_idx_close(self._h)
            self._h = None
