"""Public exception types (ref: python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception during execution; re-raised at `get`."""

    def __init__(self, cause: BaseException, traceback_str: str = ""):
        self.cause = cause
        self.traceback_str = traceback_str
        super().__init__(str(cause))

    def __str__(self):
        return f"{type(self.cause).__name__}: {self.cause}\n{self.traceback_str}"


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    """The actor is dead; its pending and future calls fail with this."""

    def __init__(self, actor_id=None, cause: str = ""):
        self.actor_id = actor_id
        self.cause = cause
        super().__init__(f"Actor {actor_id} died: {cause}")


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed from lineage."""

    def __init__(self, object_id=None):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost")


class ObjectStoreFullError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class CollectiveTimeoutError(RayTpuError, TimeoutError):
    """A collective op timed out waiting for peers (names missing ranks)."""

    def __init__(self, op: str = "collective", missing_ranks=None,
                 timeout_s: float = 0.0, detail: str = ""):
        self.op = op
        self.missing_ranks = list(missing_ranks or [])
        self.timeout_s = timeout_s
        msg = f"{op} timed out after {timeout_s:.1f}s"
        if self.missing_ranks:
            msg += f"; missing ranks: {self.missing_ranks}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class GcsTimeoutError(RayTpuError, TimeoutError):
    """A GCS control-plane RPC exceeded its bound (gcs_rpc_timeout_s)."""

    def __init__(self, method: str = "", peer: str = "",
                 timeout_s: float = 0.0):
        self.method = method
        self.peer = peer
        self.timeout_s = timeout_s
        super().__init__(
            f"GCS rpc {method!r} to {peer or '<peer>'} timed out "
            f"after {timeout_s:.1f}s")


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass
