"""Global driver/worker singleton: init/shutdown + the module-level API
(ref: python/ray/_private/worker.py — init:1285, get:2660, put:2814, wait:2879)."""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ._private.config import global_config, reset_global_config
from ._private.core_worker import CoreWorker
from ._private.ids import JobID
from ._private.node import Node
from ._private.object_ref import ObjectRef
from .actor import ActorHandle
from . import exceptions as exc

_lock = threading.RLock()
_node: Optional[Node] = None
_core: Optional[CoreWorker] = None
_driver_blackbox = None  # the driver's FlightRecorder (blackbox.py)


def _start_driver_blackbox(session_dir: Optional[str]) -> None:
    """Black-box flight ring for the driver process. Drivers usually run
    on the main thread, so the SIGTERM/SIGABRT dump handlers install; a
    SIGKILL'd driver leaves its flight file for the GCS node-death
    sweep. Skipped when the session dir is unknown (TCP-attached
    drivers on a different host than the head)."""
    global _driver_blackbox
    cfg = global_config()
    if (not cfg.blackbox_enabled or _driver_blackbox is not None
            or not session_dir or not os.path.isdir(session_dir)):
        return
    from ._private import blackbox

    def _inflight():
        c = _core
        if c is None:
            return []
        # the driver's owned in-flight submissions (core_worker._inflight)
        return [{"kind": "owned_task", "task_id": tid.hex()}
                for tid in list(getattr(c, "_inflight", {}))[:200]]

    try:
        _driver_blackbox = blackbox.FlightRecorder(
            "driver", session_dir,
            ident=f"pid-{os.getpid()}",
            ring_size=cfg.blackbox_ring_size,
            flush_interval_s=cfg.blackbox_flush_interval_s,
            inflight_provider=_inflight).start()
    except Exception:
        _driver_blackbox = None


def is_initialized() -> bool:
    return _core is not None


def core() -> CoreWorker:
    if _core is None:
        # auto-init like the reference does on first API use — but only
        # from the main thread: a background thread (e.g. a leaked data
        # pipeline stage) hitting the API after shutdown() must fail, not
        # silently resurrect a whole new cluster
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "ray_tpu is not initialized (auto-init is main-thread only)")
        init()
    return _core


def node() -> Optional[Node]:
    return _node


def init(
    address: Optional[str] = None,
    *,
    resources: Optional[Dict[str, float]] = None,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    object_store_memory: Optional[int] = None,
    labels: Optional[Dict[str, str]] = None,
    _system_config: Optional[Dict[str, Any]] = None,
    ignore_reinit_error: bool = False,
) -> Dict[str, Any]:
    """Start a local cluster, or — with ``address`` (a GCS address) —
    attach this process as a driver to an existing one (ref: ray.init
    address= semantics). Detaching drivers leave the cluster running."""
    global _node, _core
    with _lock:
        if _core is not None:
            if ignore_reinit_error:
                return {"session_name": _node.session_name if _node else ""}
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
        if _system_config:
            global_config().apply_overrides(_system_config)
        # RAY_TPU_ADDRESS: set for job-submission drivers so a bare
        # init() joins the submitting cluster (ref: RAY_ADDRESS)
        if address is None:
            address = os.environ.get("RAY_TPU_ADDRESS") or None
        if address == "local":
            address = None
        if address is not None:
            return _connect_to_address(address)
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = num_cpus
        if num_tpus is not None:
            res["TPU"] = num_tpus
        from ._private.node import default_resources

        full = default_resources()
        full.update(res)
        node = Node(head=True, resources=full, labels=labels,
                    object_store_memory=object_store_memory)
        node.start()
        return _connect_to_node(node)


def _connect_to_address(gcs_address: str) -> Dict[str, Any]:
    """Driver-only attach to a running cluster: no node is started or
    owned, so shutdown() detaches without stopping anything. Assumes a
    same-host head node (the shm store is attached directly); remote
    drivers are the future ray-client analog."""
    global _core
    from ._private.ids import NodeID, TaskID
    from ._private.object_store import SharedObjectStore
    from ._private.rpc import EventLoopThread, RpcClient

    import os

    io = EventLoopThread(name="ray_tpu_io_driver")

    async def _head_info():
        client = RpcClient(gcs_address)
        await client.connect(timeout=10)
        nodes = await client.call("get_all_nodes", {})
        await client.close()
        # pick a node whose store is reachable on THIS host: on multi-node
        # clusters get_all_nodes ordering is arbitrary and a remote node's
        # shm path would silently give us a store its raylet never sees
        for info in nodes:
            if info.alive and info.store_dir and os.path.isdir(info.store_dir):
                return info
        raise RuntimeError(
            f"no live same-host node found at {gcs_address} (remote "
            "drivers are not supported yet — run on a cluster host)")

    try:
        head = io.run(_head_info())
    except BaseException:
        io.stop()  # don't leak the io thread on a failed attach
        raise
    store = SharedObjectStore(head.store_dir,
                              global_config().object_store_memory_bytes,
                              create_dir=False)
    _core = CoreWorker(
        mode="driver",
        session_name="",
        gcs_address=gcs_address,
        raylet_address=head.address,
        job_id=JobID.from_int(0),
        node_id=head.node_id,
        store=store,
        io=io,
    )
    _core.connect()
    job_id = _core.io.run(_core.gcs.call("register_job", {"config": {}}))
    _core.job_id = job_id
    _core.current_task_id = TaskID.for_driver(job_id)
    _core.io.run(_core.gcs.call("register_driver", {"job_id": job_id}))
    # same-host attach: the raylet's unix socket lives in the session dir
    if "/" in head.address:
        _start_driver_blackbox(os.path.dirname(head.address))
    return {"gcs_address": gcs_address, "node_id": head.node_id.hex()}


def _connect_to_node(started_node: Node) -> Dict[str, Any]:
    """Attach this process as a driver of an already-started node
    (the cluster_utils / ray.init(address=...) path)."""
    global _node, _core
    with _lock:
        if _core is not None:
            raise RuntimeError("driver already connected")
        _node = started_node
        _core = CoreWorker(
            mode="driver",
            session_name=_node.session_name,
            gcs_address=_node.gcs_address,
            raylet_address=_node.raylet_address,
            job_id=JobID.from_int(1),
            node_id=_node.node_id,
            store=_node.store,
        )
        _core.connect()
        job_id = _core.io.run(_core.gcs.call("register_job", {"config": {}}))
        _core.job_id = job_id
        _core.io.run(_core.gcs.call("register_driver", {"job_id": job_id}))
        from ._private.ids import TaskID

        _core.current_task_id = TaskID.for_driver(job_id)
        _start_driver_blackbox(getattr(_node, "session_dir", None))
        return {
            "session_name": _node.session_name,
            "node_id": _node.node_id.hex(),
            "gcs_address": _node.gcs_address,
        }


def shutdown() -> None:
    """Tear the runtime down. Best-effort and idempotent (ref: ray.shutdown):
    globals are cleared FIRST so a failure mid-teardown can never strand a
    half-dead core that makes the next init() refuse to run."""
    import sys

    global _node, _core, _driver_blackbox
    with _lock:
        if _driver_blackbox is not None:
            _driver_blackbox.close(clean=True)
            _driver_blackbox = None
        if _core is not None:
            # reap live streaming_split coordinators NOW, while the RPC
            # plane is still up — leaving them to __del__ at interpreter
            # exit used to hang the process (the finalizer's kill() hit
            # auto-init, which cannot start threads during finalization)
            dataset_mod = sys.modules.get("ray_tpu.data.dataset")
            if dataset_mod is not None:
                try:
                    dataset_mod._reap_split_groups()
                except Exception:
                    pass
        core, node = _core, _node
        _core = None
        _node = None
        try:
            if core is not None:
                core.shutdown()
        finally:
            try:
                if node is not None:
                    node.stop()
            finally:
                reset_global_config()


def put(value: Any) -> ObjectRef:
    return core().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get takes ObjectRefs, got {type(r)}")
    values = core().get(ref_list, timeout)
    return values[0] if single else values


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if isinstance(refs, ObjectRef):
        raise TypeError("ray_tpu.wait takes a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns > number of refs")
    return core().wait(list(refs), num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    core().kill_actor(actor._actor_id, no_restart)


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    actor_id = core().get_named_actor(name, namespace)
    return ActorHandle(actor_id, name)


def cancel(ref, *, force: bool = False) -> None:
    """Cancel an in-flight task by its ObjectRef or ObjectRefGenerator
    (ref: python/ray/_private/worker.py:3090 ray.cancel). No-op if the task
    already finished. Actor method calls are not cancellable."""
    core().cancel(ref, force)


def get_tpu_chip_ids() -> list:
    """Physical TPU chips assigned to the current worker's lease (ref:
    accelerators/tpu.py TPU_VISIBLE_CHIPS, promoted to first-class
    per-lease scheduler state). Empty outside a TPU lease."""
    import os

    raw = os.environ.get("RAY_TPU_CHIP_IDS", "")
    return [int(x) for x in raw.split(",") if x]


def cluster_resources() -> Dict[str, float]:
    c = core()
    nodes = c.io.run(c.gcs.call("get_all_nodes", {}))
    total: Dict[str, float] = {}
    for n in nodes:
        if n.alive:
            for k, v in n.resources_total.items():
                total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    c = core()
    nodes = c.io.run(c.gcs.call("get_all_nodes", {}))
    total: Dict[str, float] = {}
    for n in nodes:
        if n.alive:
            for k, v in n.resources_available.items():
                total[k] = total.get(k, 0.0) + v
    return total


def nodes() -> List[dict]:
    import time as _time

    c = core()
    infos = c.io.run(c.gcs.call("get_all_nodes", {}))
    now = _time.time()
    out = []
    for n in infos:
        hb = getattr(n, "last_heartbeat_t", 0.0) or 0.0
        out.append({
            "NodeID": n.node_id.hex(),
            "Alive": n.alive,
            "Resources": n.resources_total,
            "Available": n.resources_available,
            "Labels": n.labels,
            "Address": n.address,
            "PendingDemands": getattr(n, "pending_demands", []),
            "ClockOffset": getattr(n, "clock_offset", 0.0),
            # None until the first heartbeat is stamped
            "HeartbeatAgeS": max(0.0, now - hb) if hb > 0 else None,
        })
    return out
