"""Results surface (ref: python/ray/tune/result_grid.py — ResultGrid wraps
per-trial Results; get_best_result picks by metric/mode)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..train._checkpoint import Checkpoint
from .trial import Trial, TrialStatus


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    config: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: str = ""
    error: Optional[str] = None

    @property
    def metrics_dataframe(self):
        raise NotImplementedError(
            "per-iteration dataframes: use ResultGrid.trial_results")


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str] = None,
                 mode: str = "max"):
        self._trials = trials
        self._metric, self._mode = metric, mode

    def __len__(self) -> int:
        return len(self._trials)

    def __getitem__(self, i: int) -> Result:
        return self._to_result(self._trials[i])

    def _to_result(self, trial: Trial) -> Result:
        ckpt = (Checkpoint(trial.checkpoint_path)
                if trial.checkpoint_path else None)
        return Result(metrics=dict(trial.last_result),
                      config=dict(trial.config),
                      checkpoint=ckpt, path=trial.local_dir,
                      error=trial.error)

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    @property
    def num_terminated(self) -> int:
        return sum(t.status == TrialStatus.TERMINATED for t in self._trials)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None,
                        scope: str = "last") -> Result:
        """Best trial by metric (ref: result_grid.py get_best_result).
        ``scope``: 'last' compares final reported values, 'all' compares
        each trial's best-ever value."""
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric is required (none set in TuneConfig)")
        best_trial, best_val = None, None
        for trial in self._trials:
            if scope == "all":
                val = trial.best_metric(metric, mode)
            else:
                val = trial.metric_value(metric)
            if val is None:
                continue
            better = (best_val is None
                      or (val > best_val if mode == "max" else val < best_val))
            if better:
                best_trial, best_val = trial, val
        if best_trial is None:
            raise RuntimeError(f"no trial reported metric {metric!r}")
        return self._to_result(best_trial)

    def trial_results(self, i: int) -> List[Dict[str, Any]]:
        """All per-iteration results of trial ``i``."""
        return [dict(r) for r in self._trials[i].results]
