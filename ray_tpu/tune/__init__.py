"""ray_tpu.tune: hyperparameter sweep library (ref: python/ray/tune/).

Trials run as core-runtime actors; schedulers (ASHA, median stopping,
PBT) early-stop and exploit across the population; results land in a
ResultGrid. Search spaces mirror ray.tune's sample API.
"""

from .result_grid import Result, ResultGrid
from .schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    qloguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from .session import get_checkpoint, get_context, get_trial_id, report
from .trial import Trial, TrialStatus
from .tuner import TuneConfig, Tuner

__all__ = [
    "Tuner", "TuneConfig", "Result", "ResultGrid", "Trial", "TrialStatus",
    "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "MedianStoppingRule", "PopulationBasedTraining", "PB2",
    "uniform", "quniform", "loguniform", "qloguniform", "randint",
    "choice", "grid_search", "sample_from", "Searcher", "TPESearcher",
    "report", "get_context", "get_checkpoint", "get_trial_id",
]
