"""Trial schedulers: early stopping and population-based training.

Reference analogs:
  - FIFO / base interface: python/ray/tune/schedulers/trial_scheduler.py
  - ASHA: python/ray/tune/schedulers/async_hyperband.py (AsyncHyperBand
    rung bracket: record a trial's value when it crosses a rung, stop it
    if it falls below the top 1/reduction_factor cutoff of that rung)
  - Median stopping: python/ray/tune/schedulers/median_stopping_rule.py
  - PBT: python/ray/tune/schedulers/pbt.py (exploit bottom-quantile trials
    from top-quantile donors + explore by perturbing hyperparams)

Schedulers are pure decision functions over controller state — they never
touch actors; the TuneController applies the returned decision.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from .search import Domain, _walk, _set_path
from .trial import Trial, TrialStatus


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"
    EXPLOIT = "EXPLOIT"  # PBT only: clone a donor's config+checkpoint

    def on_result(self, trials: List[Trial], trial: Trial,
                  result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def choose_donor(self, trials: List[Trial],
                     trial: Trial) -> Optional[Trial]:
        return None

    def mutate_config(self, config: Dict[str, Any],
                      rng: random.Random) -> Dict[str, Any]:
        return dict(config)


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""


class ASHAScheduler(TrialScheduler):
    """Async successive halving (ref: async_hyperband.py:34 _Bracket).

    Rungs sit at grace_period * reduction_factor^k for k = 0.. up to
    max_t. When a trial's ``time_attr`` crosses a rung it records its
    metric there; if it is not in the rung's top 1/reduction_factor it is
    stopped. Asynchronous: decisions use whatever has been recorded so
    far — no waiting for a full generation.
    """

    def __init__(self, metric: str, mode: str = "max", max_t: int = 100,
                 grace_period: int = 1, reduction_factor: int = 3,
                 time_attr: str = "training_iteration"):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.max_t, self.rf = max_t, reduction_factor
        self.time_attr = time_attr
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(int(t))
            t *= reduction_factor
        # rung milestone -> {trial_id: recorded value}
        self.recorded: Dict[int, Dict[str, float]] = {r: {} for r in self.rungs}

    def _cutoff(self, rung_values: Dict[str, float]) -> Optional[float]:
        """The (1 - 1/rf) percentile of the rung's recorded values
        (ref: async_hyperband.py _Bracket.cutoff — np.nanpercentile with
        linear interpolation), sign-flipped for mode=min."""
        if not rung_values:
            return None
        vals = sorted(rung_values.values())
        if self.mode == "min":
            q = 1.0 / self.rf
        else:
            q = 1.0 - 1.0 / self.rf
        pos = q * (len(vals) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(vals) - 1)
        return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)

    def on_result(self, trials, trial, result) -> str:
        if self.metric not in result:
            return self.CONTINUE
        t = int(result.get(self.time_attr, trial.iteration))
        value = float(result[self.metric])
        if t >= self.max_t:
            return self.STOP
        decision = self.CONTINUE
        for rung in reversed(self.rungs):
            if t < rung or trial.trial_id in self.recorded[rung]:
                continue
            self.recorded[rung][trial.trial_id] = value
            cutoff = self._cutoff(self.recorded[rung])
            if cutoff is not None and len(self.recorded[rung]) > 1:
                below = (value < cutoff if self.mode == "max"
                         else value > cutoff)
                if below:
                    decision = self.STOP
            break  # record at the highest rung crossed only
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of the
    running averages of completed/running trials at the same point
    (ref: median_stopping_rule.py:18)."""

    def __init__(self, metric: str, mode: str = "max",
                 grace_period: int = 5, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr

    def on_result(self, trials, trial, result) -> str:
        if self.metric not in result:
            return self.CONTINUE
        t = int(result.get(self.time_attr, trial.iteration))
        if t < self.grace_period:
            return self.CONTINUE
        means = []
        for other in trials:
            if other.trial_id == trial.trial_id:
                continue
            vals = [float(r[self.metric]) for r in other.results
                    if self.metric in r]
            if vals:
                means.append(sum(vals) / len(vals))
        if len(means) < self.min_samples:
            return self.CONTINUE
        means.sort()
        median = means[len(means) // 2]
        best = trial.best_metric(self.metric, self.mode)
        value = float(result[self.metric])
        best = value if best is None else best
        worse = (best < median if self.mode == "max" else best > median)
        return self.STOP if worse else self.CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (ref: pbt.py:304 PopulationBasedTraining._checkpoint_or_exploit):
    every ``perturbation_interval`` iterations, a bottom-quantile trial
    clones the config + latest checkpoint of a random top-quantile donor
    (exploit) and perturbs the mutation hyperparams (explore: resample
    with ``resample_probability``, else scale 0.8x/1.2x)."""

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 time_attr: str = "training_iteration",
                 seed: Optional[int] = None):
        assert 0 < quantile_fraction <= 0.5
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile_fraction = quantile_fraction
        self.resample_probability = resample_probability
        self.time_attr = time_attr
        self.rng = random.Random(seed)

    def _quantiles(self, trials: List[Trial]) -> Tuple[List[Trial], List[Trial]]:
        scored = [(t.metric_value(self.metric), t) for t in trials
                  if t.metric_value(self.metric) is not None
                  and t.status in (TrialStatus.RUNNING, TrialStatus.PENDING)]
        if len(scored) < 2:
            return [], []
        scored.sort(key=lambda p: p[0], reverse=(self.mode == "max"))
        k = max(1, int(math.ceil(len(scored) * self.quantile_fraction)))
        top = [t for _, t in scored[:k]]
        bottom = [t for _, t in scored[-k:] if t not in top]
        return top, bottom

    def on_result(self, trials, trial, result) -> str:
        if self.metric not in result:
            return self.CONTINUE
        t = int(result.get(self.time_attr, trial.iteration))
        if t - trial.last_perturbation_iter < self.interval:
            return self.CONTINUE
        trial.last_perturbation_iter = t
        top, bottom = self._quantiles(trials)
        if trial in bottom:
            donor = self.choose_donor(trials, trial)
            if donor is not None and donor.checkpoint_path:
                return self.EXPLOIT
        return self.CONTINUE

    def choose_donor(self, trials, trial) -> Optional[Trial]:
        top, _ = self._quantiles(trials)
        candidates = [t for t in top if t.checkpoint_path]
        return self.rng.choice(candidates) if candidates else None

    def mutate_config(self, config: Dict[str, Any],
                      rng: Optional[random.Random] = None) -> Dict[str, Any]:
        rng = rng or self.rng
        import copy

        out = copy.deepcopy(config)
        for path, leaf in _walk(self.mutations):
            if isinstance(leaf, Domain):
                node = out
                try:
                    for key in path[:-1]:
                        node = node[key]
                    current = node.get(path[-1])
                except (KeyError, TypeError):
                    current = None
                if current is None or rng.random() < self.resample_probability:
                    _set_path(out, path, leaf.sample(rng))
                else:
                    _set_path(out, path, leaf.perturb(current, rng))
            elif isinstance(leaf, list):
                _set_path(out, path, rng.choice(leaf))
        return out


class PB2(PopulationBasedTraining):
    """Population Based Bandits (ref: tune/schedulers/pb2.py — Parker-
    Holder et al. 2020). PBT's exploit mechanics, but EXPLORE is a
    GP-bandit: observed (hyperparams -> reward change) pairs fit a tiny
    RBF Gaussian process, and the clone's new continuous hyperparams
    maximize UCB over the search bounds instead of random 0.8x/1.2x
    scaling — far more sample-efficient at small population sizes (the
    paper's point). Non-continuous mutation leaves (choice lists) keep
    PBT behavior. Pure numpy (the reference needs GPy; nothing extra
    here)."""

    UCB_KAPPA = 1.5
    MAX_OBS = 64          # GP fit cost is O(n^3); keep the window recent

    def __init__(self, *args, **kwargs):
        from .search import Float

        super().__init__(*args, **kwargs)
        # GP-modeled dims are the FLOAT domains only: Integer leaves
        # would receive un-rounded, possibly upper-bound-exclusive
        # floats from _decode — they keep PBT perturbation instead
        self._cont_paths: List[tuple] = [
            path for path, leaf in _walk(self.mutations)
            if isinstance(leaf, Float)]
        self._domains = {path: leaf for path, leaf in _walk(self.mutations)}
        self._obs_x: List[List[float]] = []
        self._obs_y: List[float] = []
        self._last_metric: Dict[str, float] = {}

    # ---- observation stream ----

    def on_result(self, trials, trial, result) -> str:
        if self.metric in result:
            cur = float(result[self.metric])
            prev = self._last_metric.get(trial.trial_id)
            if prev is not None:
                delta = cur - prev if self.mode == "max" else prev - cur
                x = self._encode(trial.config)
                if x is not None:
                    self._obs_x.append(x)
                    self._obs_y.append(delta)
                    if len(self._obs_x) > self.MAX_OBS:
                        self._obs_x.pop(0)
                        self._obs_y.pop(0)
            self._last_metric[trial.trial_id] = cur
        decision = super().on_result(trials, trial, result)
        if decision == self.EXPLOIT:
            # the clone resumes from the DONOR's checkpoint: its next
            # metric jump is inheritance, not this config's doing —
            # recording that delta would poison the GP
            self._last_metric.pop(trial.trial_id, None)
        return decision

    # ---- GP-UCB explore ----

    def _encode(self, config) -> Optional[List[float]]:
        """Mutation hyperparams -> [0,1]^d (log-scaled where the domain
        is)."""
        out = []
        for path in self._cont_paths:
            node = config
            try:
                for key in path:
                    node = node[key]
            except (KeyError, TypeError):
                return None
            dom = self._domains[path]
            lo, hi = float(dom.lower), float(dom.upper)
            if getattr(dom, "log", False):
                out.append((math.log(node) - math.log(lo))
                           / (math.log(hi) - math.log(lo)))
            else:
                out.append((float(node) - lo) / (hi - lo))
        return out

    def _decode(self, x: List[float]):
        vals = {}
        for u, path in zip(x, self._cont_paths):
            dom = self._domains[path]
            lo, hi = float(dom.lower), float(dom.upper)
            if getattr(dom, "log", False):
                val = math.exp(math.log(lo)
                               + u * (math.log(hi) - math.log(lo)))
            else:
                val = lo + u * (hi - lo)
            if getattr(dom, "q", None):
                val = round(val / dom.q) * dom.q
            vals[path] = min(hi, max(lo, val))
        return vals

    def _gp_ucb_candidate(self) -> Optional[List[float]]:
        import numpy as np

        d = len(self._cont_paths)
        if d == 0 or len(self._obs_x) < max(3, d):
            return None
        X = np.asarray(self._obs_x, np.float64)
        y = np.asarray(self._obs_y, np.float64)
        y_std = y.std() or 1.0
        y_n = (y - y.mean()) / y_std
        length, noise = 0.3, 1e-2
        sq = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        K = np.exp(-sq / (2 * length ** 2)) + noise * np.eye(len(X))
        try:
            alpha = np.linalg.solve(K, y_n)
            K_inv = np.linalg.inv(K)
        except np.linalg.LinAlgError:
            return None
        cand = np.random.default_rng(
            self.rng.randrange(1 << 30)).random((256, d))
        sq_c = ((cand[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        k_star = np.exp(-sq_c / (2 * length ** 2))
        mu = k_star @ alpha
        var = np.maximum(1e-9, 1.0 - (k_star @ K_inv * k_star).sum(-1))
        best = int(np.argmax(mu + self.UCB_KAPPA * np.sqrt(var)))
        return cand[best].tolist()

    def mutate_config(self, config, rng=None):
        out = super().mutate_config(config, rng)   # PBT for every leaf
        x = self._gp_ucb_candidate()
        if x is not None:
            # continuous leaves: GP-UCB choice overrides the random
            # perturbation
            for path, val in self._decode(x).items():
                _set_path(out, path, val)
        return out
