"""TuneController: the event loop over trial actors.

Reference analog: python/ray/tune/execution/tune_controller.py:68 — launch
trials up to the concurrency/resource cap, poll them, feed every result to
the scheduler, apply CONTINUE/STOP/EXPLOIT decisions, retry errored trials
per FailureConfig. Trials run as TrialRunner actors scheduled by the core
runtime, so a multi-node cluster spreads trials exactly like any other
actor load.
"""

from __future__ import annotations

import logging
import os
import random
import shutil
import time
from typing import Any, Dict, List, Optional

import cloudpickle

from ..train._checkpoint import pack_dir, unpack_blob
from ..train.config import RunConfig
from .schedulers import FIFOScheduler, TrialScheduler
from .search import BasicVariantGenerator
from .trial import Trial, TrialStatus
from .runner import TrialRunner

logger = logging.getLogger("ray_tpu.tune")


class TuneController:
    POLL_INTERVAL_S = 0.1

    def __init__(self, trainable, param_space: Dict[str, Any],
                 tune_config, run_config: RunConfig):
        self.trainable = trainable
        self.tune_config = tune_config
        self.run_config = run_config
        self.scheduler: TrialScheduler = (
            tune_config.scheduler or FIFOScheduler())
        self.rng = random.Random(tune_config.seed)
        name = run_config.name or f"tune_{int(time.time())}"
        base = run_config.storage_path or "/tmp/ray_tpu_results"
        self.experiment_dir = os.path.join(base, name)
        os.makedirs(self.experiment_dir, exist_ok=True)
        self.search_alg = getattr(tune_config, "search_alg", None)
        if self.search_alg is not None:
            # suggest-based search: trials materialize lazily so each
            # suggestion can condition on completed results
            self.search_alg.set_space(param_space, tune_config.seed)
            self.trials: List[Trial] = []
            self._target_trials = tune_config.num_samples
        else:
            generator = BasicVariantGenerator(
                param_space, tune_config.num_samples, tune_config.seed)
            self.trials = [
                Trial(trial_id=f"{i:05d}", config=cfg,
                      experiment_dir=self.experiment_dir)
                for i, cfg in enumerate(generator)
            ]
            self._target_trials = len(self.trials)
        self._fn_blob = cloudpickle.dumps(trainable)
        self._actors: Dict[str, Any] = {}
        # trial_id -> (actor, start_ref, deadline): launches in flight.
        # Starts are NON-blocking — a synchronous get on actor.start
        # head-of-line blocks the control loop, so finished trials are
        # never torn down and their resources never free (deadlock when
        # free CPUs < max_concurrent, e.g. other actors on the cluster)
        self._starting: Dict[str, tuple] = {}
        self._retries: Dict[str, int] = {}

    # --- resource gating ---

    def _max_concurrent(self) -> int:
        if self.tune_config.max_concurrent_trials:
            return self.tune_config.max_concurrent_trials
        from .. import cluster_resources

        cpus = cluster_resources().get("CPU", 1.0)
        per_trial = self.tune_config.resources_per_trial.get("CPU", 1.0)
        return max(1, int(cpus // max(per_trial, 0.001)))

    # --- actor lifecycle ---

    START_TIMEOUT_S = 120.0

    def _launch(self, trial: Trial,
                restore_blob: Optional[bytes] = None) -> None:
        from .. import remote

        res = dict(self.tune_config.resources_per_trial)
        cpus = res.pop("CPU", 1.0)
        actor_cls = remote(TrialRunner)
        actor = actor_cls.options(
            num_cpus=cpus, resources=res or None, max_restarts=0,
        ).remote(trial.trial_id, trial.local_dir)
        ref = actor.start.remote(self._fn_blob, trial.config, restore_blob)
        self._starting[trial.trial_id] = (
            actor, ref, time.monotonic() + self.START_TIMEOUT_S)
        trial.status = TrialStatus.RUNNING

    def _poll_starting(self) -> None:
        """Absorb completed (or timed-out) non-blocking launches."""
        from .. import get, kill, wait
        from .. import exceptions as exc

        for tid, (actor, ref, deadline) in list(self._starting.items()):
            trial = next(t for t in self.trials if t.trial_id == tid)
            ready, _ = wait([ref], num_returns=1, timeout=0)
            if not ready:
                if time.monotonic() > deadline:
                    del self._starting[tid]
                    try:
                        kill(actor)  # don't leak a half-started runner
                    except Exception:
                        pass
                    self._on_trial_error(trial, "trial start timed out")
                continue
            del self._starting[tid]
            try:
                get(ref, timeout=10)
            except Exception as e:
                try:
                    kill(actor)
                except Exception:
                    pass
                self._on_trial_error(trial, f"trial start failed: {e}")
                continue
            self._actors[tid] = actor

    def _teardown(self, trial: Trial) -> None:
        starting = self._starting.pop(trial.trial_id, None)
        actor = self._actors.pop(trial.trial_id, None)
        if actor is None and starting is not None:
            actor = starting[0]
        if actor is None:
            return
        from .. import get, kill

        try:
            get(actor.request_stop.remote(), timeout=10)
        except Exception:
            pass
        try:
            kill(actor)
        except Exception:
            pass

    # --- checkpoint persistence ---

    def _persist_checkpoint(self, trial: Trial, path: str) -> None:
        actor = self._actors.get(trial.trial_id)
        if actor is None:
            return
        from .. import get

        try:
            blob = get(actor.pack_checkpoint.remote(path), timeout=60)
        except Exception:
            return
        if blob is None:
            return
        target = os.path.join(trial.local_dir,
                              f"checkpoint_{trial.iteration:06d}")
        unpack_blob(blob, target)
        prev = trial.checkpoint_path
        trial.checkpoint_path = target
        if prev and prev != target and os.path.isdir(prev):
            shutil.rmtree(prev, ignore_errors=True)  # keep latest only

    def _checkpoint_blob(self, trial: Trial) -> Optional[bytes]:
        if not trial.checkpoint_path or not os.path.isdir(trial.checkpoint_path):
            return None
        return pack_dir(trial.checkpoint_path)

    # --- stop criteria (ref: air RunConfig(stop={...})) ---

    def _hits_stop_criteria(self, result: Dict[str, Any]) -> bool:
        stop = self.tune_config.stop or {}
        for key, threshold in stop.items():
            if key in result and float(result[key]) >= float(threshold):
                return True
        return False

    # --- main loop ---

    def run(self) -> List[Trial]:
        try:
            while True:
                self._top_up_from_searcher()
                self._launch_pending()
                self._poll_starting()
                if not self._actors and not self._starting:
                    if (len(self.trials) >= self._target_trials
                            and all(t.status in (TrialStatus.TERMINATED,
                                                 TrialStatus.ERROR)
                                    for t in self.trials)):
                        break
                self._poll_once()
                time.sleep(self.POLL_INTERVAL_S)
        finally:
            for trial in self.trials:
                self._teardown(trial)
        return self.trials

    def _top_up_from_searcher(self) -> None:
        """Materialize trials from the searcher up to the concurrency
        window — later suggestions then see earlier completions."""
        if self.search_alg is None:
            return
        pending = sum(t.status == TrialStatus.PENDING for t in self.trials)
        while (len(self.trials) < self._target_trials
               and pending < self._max_concurrent()):
            tid = f"{len(self.trials):05d}"
            cfg = self.search_alg.suggest(tid)
            if cfg is None:  # searcher exhausted: shrink the target
                self._target_trials = len(self.trials)
                return
            self.trials.append(Trial(trial_id=tid, config=cfg,
                                     experiment_dir=self.experiment_dir))
            pending += 1

    def _launch_pending(self) -> None:
        budget = (self._max_concurrent() - len(self._actors)
                  - len(self._starting))
        for trial in self.trials:
            if budget <= 0:
                break
            if trial.status == TrialStatus.PENDING:
                try:
                    # a retried trial resumes from its persisted checkpoint
                    # (None for fresh trials)
                    self._launch(trial,
                                 restore_blob=self._checkpoint_blob(trial))
                except Exception as e:  # actor submit failed: a per-trial
                    # failure, not a sweep abort — route through the same
                    # retry policy as a mid-run crash
                    self._on_trial_error(trial, f"trial start failed: {e}")
                budget -= 1

    def _poll_once(self) -> None:
        from .. import get
        from .. import exceptions as exc

        running = [t for t in self.trials
                   if t.trial_id in self._actors]
        refs = [(t, self._actors[t.trial_id].poll.remote()) for t in running]
        for trial, ref in refs:
            try:
                status = get(ref, timeout=60)
            except (exc.ActorDiedError, exc.WorkerCrashedError,
                    exc.TaskError, exc.GetTimeoutError) as e:
                self._on_trial_error(trial, str(e))
                continue
            self._apply_status(trial, status)

    def _apply_status(self, trial: Trial, status: Dict[str, Any]) -> None:
        for rep in status["reports"]:
            trial.iteration += 1
            result = dict(rep["metrics"])
            result.setdefault("training_iteration", trial.iteration)
            trial.results.append(result)
            trial.last_result = result
            if rep.get("checkpoint_path"):
                self._persist_checkpoint(trial, rep["checkpoint_path"])
            if self._hits_stop_criteria(result):
                self._finish_trial(trial)
                return
            decision = self.scheduler.on_result(self.trials, trial, result)
            if decision == TrialScheduler.STOP:
                self._finish_trial(trial)
                return
            if decision == TrialScheduler.EXPLOIT:
                if self._exploit(trial):
                    return  # relaunched: the old runner's queue is gone
                # no viable donor: keep consuming this batch's reports
        if status["status"] == "finished":
            self._finish_trial(trial)
        elif status["status"] == "errored":
            self._on_trial_error(trial, status["error"])

    def _finish_trial(self, trial: Trial) -> None:
        self._teardown(trial)
        trial.status = TrialStatus.TERMINATED
        if self.search_alg is not None:
            self.search_alg.on_trial_complete(trial.trial_id,
                                              trial.last_result or {})

    def _on_trial_error(self, trial: Trial, error: str) -> None:
        self._teardown(trial)
        retries = self._retries.get(trial.trial_id, 0)
        if retries < self.run_config.failure_config.max_failures:
            self._retries[trial.trial_id] = retries + 1
            logger.warning("trial %s errored, retrying (%d): %s",
                           trial.trial_id, retries + 1, error.strip()[-200:])
            # roll counters/results back to what the retry actually
            # resumes from (the checkpoint's iteration, embedded in its
            # dir name; zero without one) so the failed attempt's extra
            # reports don't skew stop criteria, ASHA rungs, or the grid
            resume_at = 0
            if trial.checkpoint_path:
                tail = os.path.basename(trial.checkpoint_path)
                resume_at = int(tail.rsplit("_", 1)[-1])
            trial.iteration = resume_at
            trial.results = trial.results[:resume_at]
            trial.last_result = (dict(trial.results[-1])
                                 if trial.results else {})
            trial.status = TrialStatus.PENDING
        else:
            trial.status = TrialStatus.ERROR
            trial.error = error
            if self.search_alg is not None:
                # clear the pending slot WITHOUT a metric: an errored
                # trial's last intermediate result must not become a
                # finished observation (TPE would concentrate on a
                # config region that cannot complete; ref: searcher
                # on_trial_complete(error=True) drops the metric)
                self.search_alg.on_trial_complete(trial.trial_id, {})

    def _exploit(self, trial: Trial) -> bool:
        """PBT exploit/explore: restart this trial from a donor's
        checkpoint with a mutated clone of the donor's config
        (ref: pbt.py _exploit). Returns False when no donor checkpoint is
        available (the caller keeps the trial running)."""
        donor = self.scheduler.choose_donor(self.trials, trial)
        if donor is None or not donor.checkpoint_path:
            return False
        blob = self._checkpoint_blob(donor)
        if blob is None:
            return False
        self._teardown(trial)
        trial.config = self.scheduler.mutate_config(donor.config, self.rng)
        trial.perturbations += 1
        logger.info("PBT exploit: trial %s <- donor %s (perturbation %d)",
                    trial.trial_id, donor.trial_id, trial.perturbations)
        try:
            self._launch(trial, restore_blob=blob)
        except Exception as e:  # same per-trial policy as _launch_pending
            self._on_trial_error(trial, f"exploit relaunch failed: {e}")
        return True
