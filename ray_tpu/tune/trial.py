"""Trial state (ref: python/ray/tune/experiment/trial.py — a Trial is the
controller-side record: config, status, results, checkpoint)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class TrialStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    experiment_dir: str
    status: str = TrialStatus.PENDING
    results: List[Dict[str, Any]] = field(default_factory=list)
    last_result: Dict[str, Any] = field(default_factory=dict)
    error: Optional[str] = None
    checkpoint_path: Optional[str] = None   # latest packed checkpoint dir
    iteration: int = 0                      # training_iteration counter
    # PBT bookkeeping
    last_perturbation_iter: int = 0
    perturbations: int = 0

    @property
    def local_dir(self) -> str:
        path = os.path.join(self.experiment_dir, self.trial_id)
        os.makedirs(path, exist_ok=True)
        return path

    def metric_value(self, metric: str) -> Optional[float]:
        if metric in self.last_result:
            return float(self.last_result[metric])
        return None

    def best_metric(self, metric: str, mode: str) -> Optional[float]:
        vals = [float(r[metric]) for r in self.results if metric in r]
        if not vals:
            return None
        return max(vals) if mode == "max" else min(vals)

    def __repr__(self):
        return (f"Trial({self.trial_id}, {self.status}, "
                f"iter={self.iteration})")
