"""Search spaces and trial-config generation.

Reference analog: python/ray/tune/search/sample.py (Domain/Float/Integer/
Categorical + sampling), python/ray/tune/search/basic_variant.py
(BasicVariantGenerator — grid cross-product x num_samples random draws).
Pure-Python and deterministic under a seed; no numpy dependency so config
dicts stay pickle-friendly scalars.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class Domain:
    """A sampleable hyperparameter domain (ref: sample.py Domain)."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    # PBT mutation support: perturb an existing value within the domain.
    def perturb(self, value: Any, rng: random.Random) -> Any:
        return self.sample(rng)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            val = math.exp(rng.uniform(math.log(self.lower),
                                       math.log(self.upper)))
        else:
            val = rng.uniform(self.lower, self.upper)
        if self.q:
            val = round(val / self.q) * self.q
        return min(self.upper, max(self.lower, val))

    def perturb(self, value: Any, rng: random.Random) -> float:
        factor = rng.choice([0.8, 1.2])
        val = float(value) * factor
        if self.q:
            val = round(val / self.q) * self.q
        return min(self.upper, max(self.lower, val))


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng: random.Random) -> int:
        return rng.randint(self.lower, self.upper - 1)

    def perturb(self, value: Any, rng: random.Random) -> int:
        val = int(round(int(value) * rng.choice([0.8, 1.2])))
        return min(self.upper - 1, max(self.lower, val))


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Function(Domain):
    """Arbitrary sample function (ref: sample.py sample_from)."""

    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        return self.fn()


class Grid:
    """A grid_search axis: every value appears in the cross product
    (ref: basic_variant.py grid handling)."""

    def __init__(self, values: List[Any]):
        self.values = list(values)


# --- public constructors (ref: ray.tune.{uniform,choice,...}) ---

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _walk(space: Any, path: Tuple[str, ...] = ()):
    """Yield (path, leaf) for every Domain/Grid leaf in a nested dict."""
    if isinstance(space, dict):
        if set(space) == {"grid_search"}:
            yield path, Grid(space["grid_search"])
            return
        for key, val in space.items():
            yield from _walk(val, path + (str(key),))
    elif isinstance(space, (Domain, Grid)):
        yield path, space
    else:
        yield path, space  # constant leaf


def _set_path(cfg: Dict[str, Any], path: Tuple[str, ...], value: Any):
    node = cfg
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


class BasicVariantGenerator:
    """Resolve a param_space into concrete trial configs: the cross product
    of every grid axis, repeated ``num_samples`` times with fresh random
    draws for the stochastic domains (ref: basic_variant.py:231)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        leaves = list(_walk(param_space))
        self._grids = [(p, leaf) for p, leaf in leaves
                       if isinstance(leaf, Grid)]
        self._samplers = [(p, leaf) for p, leaf in leaves
                          if isinstance(leaf, Domain)]
        self._constants = [(p, leaf) for p, leaf in leaves
                           if not isinstance(leaf, (Domain, Grid))]

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        grid_axes = [leaf.values for _, leaf in self._grids] or [[None]]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grid_axes):
                cfg: Dict[str, Any] = {}
                for path, val in self._constants:
                    _set_path(cfg, path, val)
                if self._grids:
                    for (path, _), val in zip(self._grids, combo):
                        _set_path(cfg, path, val)
                for path, dom in self._samplers:
                    _set_path(cfg, path, dom.sample(self.rng))
                yield cfg

    def total(self) -> int:
        n_grid = 1
        for _, leaf in self._grids:
            n_grid *= len(leaf.values)
        return n_grid * self.num_samples

    def domains(self) -> Dict[Tuple[str, ...], Domain]:
        return {p: d for p, d in self._samplers}
