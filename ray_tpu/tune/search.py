"""Search spaces and trial-config generation.

Reference analog: python/ray/tune/search/sample.py (Domain/Float/Integer/
Categorical + sampling), python/ray/tune/search/basic_variant.py
(BasicVariantGenerator — grid cross-product x num_samples random draws).
Pure-Python and deterministic under a seed; no numpy dependency so config
dicts stay pickle-friendly scalars.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class Domain:
    """A sampleable hyperparameter domain (ref: sample.py Domain)."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    # PBT mutation support: perturb an existing value within the domain.
    def perturb(self, value: Any, rng: random.Random) -> Any:
        return self.sample(rng)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            val = math.exp(rng.uniform(math.log(self.lower),
                                       math.log(self.upper)))
        else:
            val = rng.uniform(self.lower, self.upper)
        if self.q:
            val = round(val / self.q) * self.q
        return min(self.upper, max(self.lower, val))

    def perturb(self, value: Any, rng: random.Random) -> float:
        factor = rng.choice([0.8, 1.2])
        val = float(value) * factor
        if self.q:
            val = round(val / self.q) * self.q
        return min(self.upper, max(self.lower, val))


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng: random.Random) -> int:
        return rng.randint(self.lower, self.upper - 1)

    def perturb(self, value: Any, rng: random.Random) -> int:
        val = int(round(int(value) * rng.choice([0.8, 1.2])))
        return min(self.upper - 1, max(self.lower, val))


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Function(Domain):
    """Arbitrary sample function (ref: sample.py sample_from)."""

    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        return self.fn()


class Grid:
    """A grid_search axis: every value appears in the cross product
    (ref: basic_variant.py grid handling)."""

    def __init__(self, values: List[Any]):
        self.values = list(values)


# --- public constructors (ref: ray.tune.{uniform,choice,...}) ---

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: List[Any]) -> Dict[str, Any]:
    return {"grid_search": list(values)}


def _walk(space: Any, path: Tuple[str, ...] = ()):
    """Yield (path, leaf) for every Domain/Grid leaf in a nested dict."""
    if isinstance(space, dict):
        if set(space) == {"grid_search"}:
            yield path, Grid(space["grid_search"])
            return
        for key, val in space.items():
            yield from _walk(val, path + (str(key),))
    elif isinstance(space, (Domain, Grid)):
        yield path, space
    else:
        yield path, space  # constant leaf


def _set_path(cfg: Dict[str, Any], path: Tuple[str, ...], value: Any):
    node = cfg
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


class Searcher:
    """Suggest-based search interface (ref: tune/search/searcher.py):
    the controller asks for one config per new trial and reports final
    results back."""

    def set_space(self, param_space: Dict[str, Any],
                  seed: Optional[int]) -> None:
        raise NotImplementedError

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any]) -> None:
        pass


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator (the hyperopt algorithm;
    ref: tune/search/hyperopt/ adapter — this environment has no
    hyperopt, so the estimator itself lives here). Observations split
    into good (top ``gamma`` quantile) and bad; each dimension draws
    candidates from a KDE over the good values and keeps the candidate
    maximizing the good/bad density ratio l(x)/g(x). Dimensions factor
    independently (standard TPE simplification)."""

    def __init__(self, metric: str, mode: str = "min", *,
                 n_initial: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._space: Dict[Tuple[str, ...], Any] = {}
        self._constants: Dict[Tuple[str, ...], Any] = {}
        self._rng = random.Random()
        self._pending: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        self._obs: List[Tuple[Dict[Tuple[str, ...], Any], float]] = []

    def set_space(self, param_space: Dict[str, Any],
                  seed: Optional[int]) -> None:
        self._rng = random.Random(seed)
        for path, leaf in _walk(param_space):
            if isinstance(leaf, Grid):
                raise ValueError(
                    "TPESearcher does not support grid_search axes; use "
                    "tune.choice for categorical dimensions")
            if isinstance(leaf, Domain):
                self._space[path] = leaf
            else:
                self._constants[path] = leaf

    # --- sampling ---

    def _random_flat(self) -> Dict[Tuple[str, ...], Any]:
        return {p: d.sample(self._rng) for p, d in self._space.items()}

    @staticmethod
    def _kde_pdf(x: float, points: List[float], bw: float) -> float:
        import math

        if not points:
            return 1e-12
        acc = 0.0
        for mu in points:
            z = (x - mu) / bw
            acc += math.exp(-0.5 * z * z)
        return acc / (len(points) * bw) + 1e-12

    def _suggest_dim(self, dom: Domain, good: List[Any],
                     bad: List[Any]) -> Any:
        import math

        if isinstance(dom, Categorical):
            cats = dom.categories
            g = {c: 1.0 for c in range(len(cats))}  # +1 smoothing
            b = {c: 1.0 for c in range(len(cats))}
            for v in good:
                g[cats.index(v)] += 1.0
            for v in bad:
                b[cats.index(v)] += 1.0
            scores = [g[i] / b[i] for i in range(len(cats))]
            total = sum(scores)
            r = self._rng.random() * total
            for i, s in enumerate(scores):  # sample ∝ ratio: explore too
                r -= s
                if r <= 0:
                    return cats[i]
            return cats[-1]
        if isinstance(dom, (Float, Integer)):
            log = bool(getattr(dom, "log", False))

            def fwd(v):
                return math.log(v) if log else float(v)

            def inv(x):
                return math.exp(x) if log else x

            lo, hi = fwd(dom.lower), fwd(dom.upper)
            gx = [fwd(v) for v in good]
            bx = [fwd(v) for v in bad]
            spread = (hi - lo) or 1.0
            mean = sum(gx) / len(gx)
            var = sum((v - mean) ** 2 for v in gx) / len(gx)
            bw = max(1.06 * math.sqrt(var) * len(gx) ** -0.2,
                     0.01 * spread)
            best_x, best_score = None, -1.0
            for _ in range(self.n_candidates):
                mu = self._rng.choice(gx)
                x = min(max(self._rng.gauss(mu, bw), lo), hi)
                score = (self._kde_pdf(x, gx, bw)
                         / self._kde_pdf(x, bx, bw))
                if score > best_score:
                    best_x, best_score = x, score
            value = inv(best_x)
            if isinstance(dom, Integer):
                return int(min(max(round(value), dom.lower),
                               dom.upper - 1))
            q = getattr(dom, "q", None)
            if q:
                value = round(value / q) * q
            return min(max(value, dom.lower), dom.upper)
        return dom.sample(self._rng)  # Function and friends: random

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        scored = self._obs
        if len(scored) < max(self.n_initial, 2):
            flat = self._random_flat()
        else:
            ordered = sorted(scored, key=lambda o: o[1],
                             reverse=(self.mode == "max"))
            n_good = max(1, int(len(ordered) * self.gamma))
            good_obs = ordered[:n_good]
            bad_obs = ordered[n_good:] or ordered[-1:]
            flat = {}
            for path, dom in self._space.items():
                good = [o[0][path] for o in good_obs if path in o[0]]
                bad = [o[0][path] for o in bad_obs if path in o[0]]
                flat[path] = (self._suggest_dim(dom, good, bad)
                              if good and bad else dom.sample(self._rng))
        self._pending[trial_id] = flat
        cfg: Dict[str, Any] = {}
        for path, val in self._constants.items():
            _set_path(cfg, path, val)
        for path, val in flat.items():
            _set_path(cfg, path, val)
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any]) -> None:
        flat = self._pending.pop(trial_id, None)
        if flat is None or self.metric not in result:
            return
        self._obs.append((flat, float(result[self.metric])))


class BasicVariantGenerator:
    """Resolve a param_space into concrete trial configs: the cross product
    of every grid axis, repeated ``num_samples`` times with fresh random
    draws for the stochastic domains (ref: basic_variant.py:231)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        leaves = list(_walk(param_space))
        self._grids = [(p, leaf) for p, leaf in leaves
                       if isinstance(leaf, Grid)]
        self._samplers = [(p, leaf) for p, leaf in leaves
                          if isinstance(leaf, Domain)]
        self._constants = [(p, leaf) for p, leaf in leaves
                           if not isinstance(leaf, (Domain, Grid))]

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        grid_axes = [leaf.values for _, leaf in self._grids] or [[None]]
        for _ in range(self.num_samples):
            for combo in itertools.product(*grid_axes):
                cfg: Dict[str, Any] = {}
                for path, val in self._constants:
                    _set_path(cfg, path, val)
                if self._grids:
                    for (path, _), val in zip(self._grids, combo):
                        _set_path(cfg, path, val)
                for path, dom in self._samplers:
                    _set_path(cfg, path, dom.sample(self.rng))
                yield cfg

    def total(self) -> int:
        n_grid = 1
        for _, leaf in self._grids:
            n_grid *= len(leaf.values)
        return n_grid * self.num_samples

    def domains(self) -> Dict[Tuple[str, ...], Domain]:
        return {p: d for p, d in self._samplers}
