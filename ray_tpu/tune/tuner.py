"""Tuner: the public entry point (ref: python/ray/tune/tuner.py:312
Tuner.fit; tune_config in python/ray/tune/tune_config.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..train.config import RunConfig
from .controller import TuneController
from .result_grid import ResultGrid
from .schedulers import TrialScheduler


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    resources_per_trial: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})
    seed: Optional[int] = None
    # stop criteria applied to every result, e.g. {"training_iteration": 50}
    # (ref: air.RunConfig(stop=...); kept here so RunConfig stays shared
    # with Train)
    stop: Optional[Dict[str, float]] = None
    # suggest-based searcher (ref: tune/search/ — optuna/hyperopt
    # adapters there; here the native TPESearcher or any Searcher
    # subclass). None = BasicVariantGenerator grid/random resolution.
    search_alg: Optional[Any] = None


class Tuner:
    """Run a hyperparameter sweep over a trainable.

    The trainable is a function ``fn(config)`` that calls
    ``ray_tpu.tune.report(metrics, checkpoint=...)`` each iteration — a
    ray_tpu.train.Trainer can be nested inside it for distributed trials
    (the reference's Train-in-Tune composition).
    """

    def __init__(self, trainable, *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if not callable(trainable):
            raise TypeError("trainable must be a callable fn(config)")
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        controller = TuneController(
            self.trainable, self.param_space, self.tune_config,
            self.run_config)
        trials = controller.run()
        return ResultGrid(trials, self.tune_config.metric,
                          self.tune_config.mode)
