"""Trial-runner actor: hosts one trial's trainable function.

Reference analog: python/ray/tune/trainable/function_trainable.py
(FunctionTrainable wraps the user fn on a thread and exchanges results
through the session) + the trial-actor lifecycle TuneController drives
(tune/execution/tune_controller.py).
"""

from __future__ import annotations

import inspect
import os
import threading
import traceback
from typing import Any, Dict, Optional

import cloudpickle

from ..train._checkpoint import Checkpoint
from .session import TuneContext, TrialStopped, _init_session, _shutdown_session


class TrialRunner:
    """One actor per running trial (module-level for worker-side import)."""

    def __init__(self, trial_id: str, trial_dir: str):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self._thread: Optional[threading.Thread] = None
        self._session = None
        self._error: Optional[str] = None
        self._finished = False
        self._stopped = False

    def start(self, fn_blob: bytes, config: Dict[str, Any],
              restore_blob: Optional[bytes] = None) -> bool:
        restored = None
        if restore_blob is not None:
            from ..train._checkpoint import unpack_blob

            restored = Checkpoint(unpack_blob(restore_blob))
        context = TuneContext(trial_id=self.trial_id,
                              trial_dir=self.trial_dir,
                              restored_checkpoint=restored)
        self._session = _init_session(context)
        trainable = cloudpickle.loads(fn_blob)

        def _run():
            try:
                if len(inspect.signature(trainable).parameters) >= 1:
                    trainable(config)
                else:
                    trainable()
                self._finished = True
            except TrialStopped:
                self._finished = True
            except BaseException:  # noqa: BLE001 — surfaced via poll()
                self._error = traceback.format_exc()

        self._thread = threading.Thread(
            target=_run, daemon=True, name=f"trial_{self.trial_id}")
        self._thread.start()
        return True

    def poll(self) -> Dict[str, Any]:
        # status snapshot BEFORE the drain: a report appended between the
        # drain and the flag read would otherwise vanish — the controller
        # sees 'finished', tears us down, and the final metrics are lost.
        # Reading the flags first means that race surfaces as one extra
        # 'running' poll instead.
        error, finished = self._error, self._finished
        reports = []
        if self._session is not None:
            for rep in self._session.drain():
                reports.append({
                    "metrics": rep.metrics,
                    "checkpoint_path":
                        rep.checkpoint.path if rep.checkpoint else None,
                })
        if error is not None:
            status = "errored"
        elif finished:
            status = "finished"
        elif self._thread is not None:
            status = "running"
        else:
            status = "idle"
        return {"trial_id": self.trial_id, "status": status,
                "error": error, "reports": reports}

    def request_stop(self) -> bool:
        """Cooperative stop: the trainable's next report() raises
        TrialStopped (the function-API analog of Trainable.stop)."""
        self._stopped = True
        if self._session is not None:
            self._session.stop_requested = True
        return True

    def pack_checkpoint(self, path: str) -> Optional[bytes]:
        """Tar a reported checkpoint dir so the controller can persist it
        into trial storage regardless of which host the trial ran on."""
        from ..train._checkpoint import pack_dir

        if not os.path.isdir(path):
            return None
        return pack_dir(path)

    def shutdown(self) -> bool:
        _shutdown_session()
        return True
