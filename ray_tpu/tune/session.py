"""Trial-side session: ``ray_tpu.tune.report`` inside a trainable
(ref: python/ray/tune/trainable/function_trainable.py — the function-API
session a trial's user code reports through).

Mirrors ray_tpu.train.session but per-trial: one session per trial-runner
actor process; reports carry metrics plus an optional checkpoint
directory the controller packs into trial storage (PBT exploit needs it).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..train._checkpoint import Checkpoint


@dataclass
class TuneContext:
    trial_id: str
    trial_dir: str
    restored_checkpoint: Optional[Checkpoint] = None


@dataclass
class _Report:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None


class _Session:
    def __init__(self, context: TuneContext):
        self.context = context
        self.reports: List[_Report] = []
        self.lock = threading.Lock()
        self.stop_requested = False

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint]) -> None:
        with self.lock:
            self.reports.append(_Report(dict(metrics), checkpoint))

    def drain(self) -> List[_Report]:
        with self.lock:
            pending, self.reports = self.reports, []
        return pending


_session: Optional[_Session] = None


def _init_session(context: TuneContext) -> _Session:
    global _session
    _session = _Session(context)
    return _session


def _shutdown_session() -> None:
    global _session
    _session = None


def _require_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "ray_tpu.tune.report/get_context can only be called inside a "
            "trainable launched by Tuner.fit()")
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report one iteration's metrics (ref: ray.tune.report). Raising
    ``StopIteration``-like early exit: if the scheduler stopped this trial
    the next report raises ``TrialStopped`` so user loops unwind."""
    session = _require_session()
    if session.stop_requested:
        raise TrialStopped()
    session.report(metrics, checkpoint)


def get_context() -> TuneContext:
    return _require_session().context


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from (set on PBT exploit / trial restore)."""
    return _require_session().context.restored_checkpoint


def get_trial_id() -> str:
    return _require_session().context.trial_id


class TrialStopped(BaseException):
    """Raised inside a trainable when the scheduler stopped the trial;
    BaseException so a blanket ``except Exception`` in user code cannot
    swallow the unwind (ref: tune's StopIteration-based session stop)."""
