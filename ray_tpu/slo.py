"""SLO engine: declarative service-level objectives evaluated against
the GCS metric time series, with multi-window burn-rate alerting.

Three pieces, all head-side and control-plane only:

* :class:`SeriesStore` — bounded per-series ring buffers of downsampled
  (timestamp, value) samples. The GCS samples its aggregated metrics
  table into one of these on its evaluation tick (the in-memory-TSDB
  role Monarch plays for Google's alerting; see PAPERS.md).
* :class:`SloSpec` / :func:`parse_specs` — declarative objectives like
  ``"chat-ttft: ttft_p99 < 250ms @ tenant=acme"`` or
  ``"chat-avail: availability >= 99.9% @ deployment=Chat"``.
* :class:`SloMonitor` — evaluates every spec each tick: windowed
  attainment (interpolated over histogram bucket deltas), plus fast and
  slow multi-window burn-rate alerts (Google SRE Workbook ch. 5: alert
  when the error-budget burn rate exceeds a threshold over BOTH a short
  and a long window — the short window gives speed, the long window
  stops a transient blip from paging). Fast-burn fires an ERROR cluster
  event, slow-burn a WARNING, recovery an INFO; state transitions only,
  never a re-fire per tick.

The math (windowed counter increase, interpolated histogram quantiles /
good-fractions) lives in ``ray_tpu/util/metrics.py`` so it is shared
with local introspection and unit-testable without a cluster.
"""

from __future__ import annotations

import collections
import re
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .util.metrics import (histogram_good_fraction, histogram_quantile,
                           windowed_increase)

# indicator aliases: the short names specs use -> (metric, kind). A full
# metric name is also accepted (e.g. "serve_http_request_seconds_p95").
INDICATOR_ALIASES: Dict[str, str] = {
    "ttft": "llm_ttft_seconds",
    "tpot": "llm_tpot_seconds",
    "e2e": "llm_request_e2e_seconds",
    "latency": "serve_request_e2e_seconds",
    "http_latency": "serve_http_request_seconds",
    "step_time": "train_step_seconds",
}
# availability is derived: errors / total requests under the selector
AVAILABILITY_ERRORS_METRIC = "serve_request_errors_total"
AVAILABILITY_TOTAL_METRIC = "serve_request_e2e_seconds"

# floor indicators: gauges that must stay ABOVE a threshold ("mfu >=
# 0.4"). A sample below the floor is a bad event; the objective is
# pinned at 0.99 so an all-bad window burns budget at 100x — squarely
# past the fast-burn threshold — instead of the ~2x cap a
# threshold-as-objective reading would give (which could never page).
FLOOR_INDICATORS: Dict[str, str] = {
    "mfu": "train_mfu",
    "goodput": "train_goodput_fraction",
    "tok_per_chip": "train_tokens_per_s_per_chip",
}
FLOOR_OBJECTIVE = 0.99

_QUANTILE_RE = re.compile(r"^(?P<base>.+)_p(?P<q>\d+(?:\.\d+)?)$")
_VALUE_RE = re.compile(
    r"^(?P<num>\d+(?:\.\d+)?)\s*(?P<unit>ms|us|s|%)?$")


class SpecError(ValueError):
    """A malformed SLO spec string/dict (named so config typos surface
    as one attributed error, not a tick-loop crash)."""


def parse_value(text: str) -> float:
    """``250ms`` -> 0.25, ``1.5s`` -> 1.5, ``99.9%`` -> 0.999, bare
    floats pass through."""
    m = _VALUE_RE.match(str(text).strip())
    if not m:
        raise SpecError(f"unparseable threshold {text!r}")
    num = float(m.group("num"))
    unit = m.group("unit")
    if unit == "ms":
        return num / 1e3
    if unit == "us":
        return num / 1e6
    if unit == "%":
        return num / 100.0
    return num


@dataclass
class SloSpec:
    name: str                      # display name ("chat-ttft")
    indicator: str                 # as written ("ttft_p99", "availability")
    kind: str                      # "quantile" | "availability" | "floor"
    metric: str                    # resolved histogram/counter/gauge metric
    quantile: float                # target quantile (quantile kind)
    op: str                        # "<", "<=", ">=", ">"
    threshold: float               # seconds (quantile), ratio (avail.),
    #                                or gauge floor value (floor)
    window_s: float = 60.0         # attainment window
    selector: Dict[str, str] = field(default_factory=dict)

    @property
    def objective(self) -> float:
        """Target good-event ratio: p99 -> 0.99; availability -> the
        threshold itself; floor -> FLOOR_OBJECTIVE (the threshold is a
        gauge value, not a ratio). 1 - objective is the error budget
        burn rates are measured against."""
        return (self.threshold if self.kind == "availability"
                else self.quantile)

    def describe(self) -> str:
        sel = ",".join(f"{k}={v}" for k, v in sorted(self.selector.items()))
        return (f"{self.name}: {self.indicator} {self.op} "
                f"{self.threshold:g}" + (f" @ {sel}" if sel else ""))


def _parse_one(entry: Any) -> SloSpec:
    if isinstance(entry, SloSpec):
        return entry
    if isinstance(entry, dict):
        d = dict(entry)
        text = (f"{d.pop('name')}: {d.pop('indicator')} "
                f"{d.pop('op', '<')} {d.pop('threshold')}")
        spec = _parse_str(text)
        if "window_s" in d:
            spec.window_s = float(d.pop("window_s"))
        if "selector" in d:
            spec.selector = {str(k): str(v)
                             for k, v in d.pop("selector").items()}
        return spec
    return _parse_str(str(entry))


def _parse_str(text: str) -> SloSpec:
    """Grammar: ``name: indicator op value [@ k=v,k=v] [window=30s]``."""
    head, sep, rest = text.partition(":")
    if not sep or not rest.strip():
        raise SpecError(f"SLO spec needs 'name: objective': {text!r}")
    name = head.strip()
    rest = rest.strip()
    window_s = 60.0
    wm = re.search(r"\bwindow\s*=\s*(\S+)", rest)
    if wm:
        window_s = parse_value(wm.group(1))
        rest = (rest[:wm.start()] + rest[wm.end():]).strip()
    selector: Dict[str, str] = {}
    body, at, sel = rest.partition("@")
    if at:
        for pair in sel.split(","):
            pair = pair.strip()
            if not pair:
                continue
            if "=" not in pair:
                raise SpecError(f"selector needs k=v pairs: {text!r}")
            k, _, v = pair.partition("=")
            selector[k.strip()] = v.strip()
    m = re.match(r"^(?P<ind>\S+)\s*(?P<op><=|>=|<|>)\s*(?P<val>\S+)$",
                 body.strip())
    if not m:
        raise SpecError(f"SLO spec needs 'indicator op value': {text!r}")
    indicator, op, value = m.group("ind"), m.group("op"), m.group("val")
    threshold = parse_value(value)
    if indicator == "availability":
        if op not in (">=", ">"):
            raise SpecError(f"availability wants '>=': {text!r}")
        if not 0.0 < threshold <= 1.0:
            raise SpecError(f"availability target out of (0,1]: {text!r}")
        return SloSpec(name=name, indicator=indicator,
                       kind="availability",
                       metric=AVAILABILITY_TOTAL_METRIC,
                       quantile=threshold, op=op, threshold=threshold,
                       window_s=window_s, selector=selector)
    if indicator in FLOOR_INDICATORS:
        if op not in (">=", ">"):
            raise SpecError(
                f"{indicator} is a floor indicator, wants '>=': {text!r}")
        return SloSpec(name=name, indicator=indicator, kind="floor",
                       metric=FLOOR_INDICATORS[indicator],
                       quantile=FLOOR_OBJECTIVE, op=op,
                       threshold=threshold, window_s=window_s,
                       selector=selector)
    qm = _QUANTILE_RE.match(indicator)
    if not qm:
        raise SpecError(
            f"unknown indicator {indicator!r} (want availability or "
            f"<metric>_p<q>): {text!r}")
    base = qm.group("base")
    metric = INDICATOR_ALIASES.get(base, base)
    if base == "step_time":
        # train_step_seconds carries one series per phase; without a
        # phase pin a quantile over it would sum buckets across phases
        # and double-count every step. The step wall is phase=total.
        selector.setdefault("phase", "total")
    q = float(qm.group("q")) / 100.0
    if not 0.0 < q < 1.0:
        raise SpecError(f"quantile out of (0,100): {text!r}")
    if op not in ("<", "<="):
        raise SpecError(f"latency quantile wants '<': {text!r}")
    return SloSpec(name=name, indicator=indicator, kind="quantile",
                   metric=metric, quantile=q, op=op, threshold=threshold,
                   window_s=window_s, selector=selector)


def parse_specs(entries: Any) -> List[SloSpec]:
    """Parse a config-shaped spec list (list of strings/dicts, or one
    ``|``-separated string). Duplicate names keep the last entry."""
    if entries is None:
        return []
    if isinstance(entries, str):
        entries = [e for e in entries.split("|") if e.strip()]
    out: Dict[str, SloSpec] = {}
    for entry in entries:
        spec = _parse_one(entry)
        out[spec.name] = spec
    return list(out.values())


# ---------------------------------------------------------- series store
class SeriesStore:
    """Bounded per-series ring buffers of downsampled samples.

    Keyed like the GCS aggregated metrics view: (metric name, sorted
    tag tuple). Appends closer together than ``min_interval_s`` are
    dropped (downsampling), each series keeps at most ``max_samples``
    points (retention = max_samples x sample interval), and the store
    holds at most ``max_series`` series with FIFO eviction — the same
    bound discipline as the GCS last-value metrics table."""

    def __init__(self, max_samples: int = 256,
                 min_interval_s: float = 2.0,
                 max_series: int = 4000):
        self.max_samples = max(2, int(max_samples))
        self.min_interval_s = float(min_interval_s)
        self.max_series = max(1, int(max_series))
        self._series: "collections.OrderedDict[Tuple[str, tuple], dict]" = \
            collections.OrderedDict()

    def __len__(self) -> int:
        return len(self._series)

    def sample(self, entries: Sequence[Dict[str, Any]],
               t: Optional[float] = None) -> int:
        """Append one sampling tick of aggregated metric entries
        (handle_get_metrics layout). Returns how many series advanced."""
        if t is None:
            t = time.time()
        appended = 0
        for e in entries:
            key = (e["name"], tuple(sorted((e.get("tags") or {}).items())))
            rec = self._series.get(key)
            if rec is None:
                while len(self._series) >= self.max_series:
                    self._series.popitem(last=False)
                rec = self._series[key] = {
                    "kind": e.get("kind", "gauge"), "last_t": -1e18,
                    "samples": collections.deque(maxlen=self.max_samples),
                }
            if t - rec["last_t"] < self.min_interval_s:
                continue
            rec["last_t"] = t
            rec["samples"].append((t, float(e["value"])))
            appended += 1
        return appended

    @staticmethod
    def _matches(tags: Dict[str, str], selector: Dict[str, str]) -> bool:
        return all(tags.get(k) == v for k, v in selector.items())

    def query(self, name: str,
              selector: Optional[Dict[str, str]] = None
              ) -> List[Dict[str, Any]]:
        """Series for one metric whose tags satisfy the selector
        (internal ``le``/``__stat__`` keys never participate in
        matching)."""
        selector = selector or {}
        out = []
        for (n, tag_t), rec in self._series.items():
            if n != name:
                continue
            tags = dict(tag_t)
            plain = {k: v for k, v in tags.items()
                     if k not in ("le", "__stat__")}
            if not self._matches(plain, selector):
                continue
            out.append({"name": n, "tags": tags, "kind": rec["kind"],
                        "samples": list(rec["samples"])})
        return out

    def dump(self) -> Dict[str, Any]:
        """Checkpointable snapshot (plain lists/tuples — pickles and
        JSON-encodes; the GCS persists this through gcs_storage so the
        rings survive a head restart)."""
        return {
            "version": 1,
            "max_samples": self.max_samples,
            "min_interval_s": self.min_interval_s,
            "series": [
                {"name": n, "tags": list(tag_t), "kind": rec["kind"],
                 "last_t": rec["last_t"],
                 "samples": [list(s) for s in rec["samples"]]}
                for (n, tag_t), rec in self._series.items()
            ],
        }

    def load(self, state: Dict[str, Any]) -> int:
        """Restore a dump() snapshot into this (empty or live) store.
        Restored samples land BEHIND anything already present-by-key;
        current bounds win over the checkpoint's. Returns the number of
        series restored."""
        loaded = 0
        for ser in state.get("series", []):
            key = (ser["name"],
                   tuple(tuple(p) for p in ser.get("tags", [])))
            if key in self._series:
                continue  # live data is newer than the checkpoint
            while len(self._series) >= self.max_series:
                self._series.popitem(last=False)
            samples = collections.deque(
                (tuple(s) for s in ser.get("samples", [])),
                maxlen=self.max_samples)
            self._series[key] = {
                "kind": ser.get("kind", "gauge"),
                "last_t": float(ser.get("last_t", -1e18)),
                "samples": samples,
            }
            loaded += 1
        return loaded

    def bucket_increases(self, name: str, selector: Dict[str, str],
                         window_s: float, now: float
                         ) -> List[Tuple[float, float]]:
        """Windowed histogram bucket deltas: per ``le`` bound, the
        summed increase over the trailing window across every matching
        series. The per-``le`` counts are cumulative-by-bound, so the
        result feeds histogram_quantile/good_fraction directly."""
        by_bound: Dict[float, float] = {}
        for rec in self.query(name, selector):
            le = rec["tags"].get("le")
            if le is None:
                continue
            bound = float("inf") if le == "+Inf" else float(le)
            inc = windowed_increase(rec["samples"], window_s, now)
            by_bound[bound] = by_bound.get(bound, 0.0) + inc
        return sorted(by_bound.items())

    def stat_increase(self, name: str, selector: Dict[str, str],
                      window_s: float, now: float,
                      stat: str = "count") -> float:
        """Windowed increase of a histogram's ``__stat__`` series
        (count/sum) summed across matching series."""
        total = 0.0
        for rec in self.query(name, selector):
            if rec["tags"].get("__stat__") != stat:
                continue
            total += windowed_increase(rec["samples"], window_s, now)
        return total

    def counter_increase(self, name: str, selector: Dict[str, str],
                         window_s: float, now: float) -> float:
        """Windowed increase of a plain counter summed across matching
        series."""
        total = 0.0
        for rec in self.query(name, selector):
            if "le" in rec["tags"] or "__stat__" in rec["tags"]:
                continue
            total += windowed_increase(rec["samples"], window_s, now)
        return total


# ------------------------------------------------------------ evaluation
def error_ratio(spec: SloSpec, store: SeriesStore, window_s: float,
                now: float) -> Tuple[Optional[float], float]:
    """(bad-event ratio over the window, total events). None ratio means
    no traffic in the window (vacuously compliant, burn 0)."""
    if spec.kind == "availability":
        total = store.stat_increase(AVAILABILITY_TOTAL_METRIC,
                                    spec.selector, window_s, now)
        if total <= 0:
            return None, 0.0
        errors = store.counter_increase(AVAILABILITY_ERRORS_METRIC,
                                        spec.selector, window_s, now)
        return min(1.0, errors / total), total
    if spec.kind == "floor":
        # gauge floor: each in-window sample below the threshold is a
        # bad event — an all-bad window burns at 1/(1-FLOOR_OBJECTIVE)
        # = 100x, well past any burn-policy threshold
        lo = now - window_s
        bad = total = 0.0
        for rec in store.query(spec.metric, spec.selector):
            if "le" in rec["tags"] or "__stat__" in rec["tags"]:
                continue
            for t, v in rec["samples"]:
                if t < lo:
                    continue
                total += 1
                if (v < spec.threshold if spec.op == ">="
                        else v <= spec.threshold):
                    bad += 1
        if total <= 0:
            return None, 0.0
        return bad / total, total
    buckets = store.bucket_increases(spec.metric, spec.selector,
                                     window_s, now)
    if not buckets:
        return None, 0.0
    total = max((c for _, c in buckets), default=0.0)
    good = histogram_good_fraction(spec.threshold, buckets)
    if good is None:
        return None, 0.0
    return 1.0 - good, total


def burn_rate(spec: SloSpec, store: SeriesStore, window_s: float,
              now: float) -> float:
    """Error-budget burn rate over a window: error_ratio / (1 - objective).
    1.0 = burning exactly the budget; 14.4 over 5m/1h is the SRE
    Workbook's classic page threshold."""
    budget = max(1e-9, 1.0 - spec.objective)
    ratio, _total = error_ratio(spec, store, window_s, now)
    if ratio is None:
        return 0.0
    return ratio / budget


@dataclass
class BurnPolicy:
    """One multi-window burn alert: fires when the burn rate exceeds
    ``threshold`` over BOTH windows (short = fast detection, long =
    transient suppression)."""
    severity: str          # "ERROR" (fast) / "WARNING" (slow)
    kind: str              # "fast_burn" / "slow_burn"
    short_window_s: float
    long_window_s: float
    threshold: float

    def firing(self, spec: SloSpec, store: SeriesStore,
               now: float) -> Tuple[bool, float, float]:
        short = burn_rate(spec, store, self.short_window_s, now)
        long = burn_rate(spec, store, self.long_window_s, now)
        return (short >= self.threshold and long >= self.threshold,
                short, long)


def default_policies(cfg) -> List[BurnPolicy]:
    """Fast+slow pair from config knobs (SRE Workbook table 5-3 scaled
    to this cluster's 2 s sampling tick)."""

    def _pair(text: str, fallback: Tuple[float, float]):
        try:
            a, b = (float(x) for x in str(text).split(","))
            return a, b
        except Exception:
            return fallback

    fs, fl = _pair(cfg.slo_fast_burn_windows_s, (30.0, 300.0))
    ss, sl = _pair(cfg.slo_slow_burn_windows_s, (120.0, 600.0))
    return [
        BurnPolicy("ERROR", "fast_burn", fs, fl,
                   float(cfg.slo_fast_burn_threshold)),
        BurnPolicy("WARNING", "slow_burn", ss, sl,
                   float(cfg.slo_slow_burn_threshold)),
    ]


_STATE_RANK = {"ok": 0, "slow_burn": 1, "fast_burn": 2}


class SloMonitor:
    """Per-spec evaluation state: attainment history ring + burn-alert
    state machine. The GCS owns one and ticks it on its evaluation
    loop; events go out through the supplied emitter (the GCS _event
    hook) only on state TRANSITIONS."""

    def __init__(self, specs: Sequence[SloSpec],
                 policies: Sequence[BurnPolicy],
                 history_len: int = 240):
        self.policies = list(policies)
        self.history_len = int(history_len)
        self._state: Dict[str, dict] = {}
        # restore grace: after a head restart reloads this monitor, new
        # ok->firing transitions are suppressed until the window refills
        # with live samples (the gap itself must never page)
        self._grace_until: float = 0.0
        self.set_specs(specs)

    def set_specs(self, specs: Sequence[SloSpec]) -> None:
        self.specs = list(specs)
        live = {s.name for s in self.specs}
        for name in [n for n in self._state if n not in live]:
            del self._state[name]
        for spec in self.specs:
            self._state.setdefault(spec.name, {
                "alert": "ok",
                "since": None,
                "history": collections.deque(maxlen=self.history_len),
            })

    def tick(self, store: SeriesStore, now: Optional[float] = None,
             emit: Optional[Callable[..., None]] = None) -> None:
        """Evaluate every spec; ``emit(severity, message, **fields)``
        receives alert transitions."""
        if now is None:
            now = time.time()
        for spec in self.specs:
            st = self._state[spec.name]
            ratio, total = error_ratio(spec, store, spec.window_s, now)
            attainment = None if ratio is None else 1.0 - ratio
            achieved = None
            if spec.kind == "quantile":
                buckets = store.bucket_increases(
                    spec.metric, spec.selector, spec.window_s, now)
                achieved = histogram_quantile(spec.quantile, buckets)
            elif spec.kind == "floor":
                # latest in-window gauge value (what the floor guards)
                lo, best_t = now - spec.window_s, None
                for rec in store.query(spec.metric, spec.selector):
                    if "le" in rec["tags"] or "__stat__" in rec["tags"]:
                        continue
                    for t, v in rec["samples"]:
                        if t >= lo and (best_t is None or t >= best_t):
                            best_t, achieved = t, v
            compliant = (attainment is None
                         or attainment >= spec.objective)
            alert, burns = "ok", {}
            for pol in self.policies:
                firing, short, long = pol.firing(spec, store, now)
                burns[pol.kind] = {"short": round(short, 3),
                                   "long": round(long, 3),
                                   "threshold": pol.threshold,
                                   "firing": firing}
                if firing and _STATE_RANK[pol.kind] > _STATE_RANK[alert]:
                    alert = pol.kind
            prev = st["alert"]
            if (alert != prev and now < self._grace_until
                    and _STATE_RANK[alert] > _STATE_RANK[prev]):
                alert = prev  # restore grace: escalations wait it out
            if alert != prev:
                st["alert"] = alert
                st["since"] = now
                if emit is not None:
                    if alert == "ok":
                        emit("INFO", f"SLO '{spec.name}' recovered "
                             f"({spec.describe()})",
                             kind="slo_recovered", slo=spec.name,
                             burns=burns)
                    else:
                        pol = next(p for p in self.policies
                                   if p.kind == alert)
                        emit(pol.severity,
                             f"SLO '{spec.name}' {alert.replace('_', '-')}"
                             f": burning error budget at "
                             f"{burns[alert]['short']:g}x over "
                             f"{pol.short_window_s:g}s and "
                             f"{burns[alert]['long']:g}x over "
                             f"{pol.long_window_s:g}s "
                             f"({spec.describe()})",
                             kind=alert, slo=spec.name,
                             attainment=attainment, burns=burns)
            st["history"].append({
                "t": now,
                "attainment": (None if attainment is None
                               else round(attainment, 6)),
                "achieved": (None if achieved is None
                             else round(achieved, 6)),
                "total": round(total, 1),
                "alert": alert,
            })
            st["last"] = {
                "attainment": attainment, "achieved": achieved,
                "total": total, "compliant": compliant, "burns": burns,
            }

    def dump(self) -> Dict[str, Any]:
        """Checkpointable snapshot of the alert state machine + history
        rings (specs themselves ride config / the GCS KV, not this)."""
        return {
            "version": 1,
            "state": {
                name: {"alert": st["alert"], "since": st["since"],
                       "history": [dict(h) for h in st["history"]]}
                for name, st in self._state.items()
            },
        }

    def load(self, state: Dict[str, Any], now: Optional[float] = None,
             grace_s: float = 0.0) -> int:
        """Restore a dump() snapshot for the specs currently installed;
        unknown names are dropped. ``grace_s`` suppresses new alert
        escalations for that long after ``now`` (head-restart gap)."""
        if now is None:
            now = time.time()
        restored = 0
        for name, saved in (state.get("state") or {}).items():
            st = self._state.get(name)
            if st is None:
                continue
            st["alert"] = saved.get("alert", "ok")
            st["since"] = saved.get("since")
            st["history"] = collections.deque(
                saved.get("history", []), maxlen=self.history_len)
            restored += 1
        if grace_s > 0:
            self._grace_until = max(self._grace_until, now + grace_s)
        return restored

    def status(self) -> List[Dict[str, Any]]:
        """API-shaped view: one record per spec with current attainment,
        burn rates, alert state, and the attainment history ring."""
        out = []
        for spec in self.specs:
            st = self._state[spec.name]
            last = st.get("last", {})
            out.append({
                "name": spec.name,
                "spec": spec.describe(),
                "indicator": spec.indicator,
                "metric": spec.metric,
                "kind": spec.kind,
                "objective": spec.objective,
                "threshold": spec.threshold,
                "window_s": spec.window_s,
                "selector": dict(spec.selector),
                "attainment": last.get("attainment"),
                "achieved": last.get("achieved"),
                "total": last.get("total", 0.0),
                "compliant": last.get("compliant", True),
                "burns": last.get("burns", {}),
                "alert": st["alert"],
                "alert_since": st["since"],
                "history": list(st["history"]),
            })
        return out
