"""RemoteFunction: the @remote task wrapper (ref: python/ray/remote_function.py:303)."""

from __future__ import annotations

from typing import Any, Dict, Optional


class RemoteFunction:
    def __init__(self, func, options: Optional[Dict[str, Any]] = None):
        self._function = func
        self._options = dict(options or {})
        self.__name__ = getattr(func, "__name__", "remote_function")
        self.__doc__ = getattr(func, "__doc__", None)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self.__name__}' cannot be called directly; "
            f"use {self.__name__}.remote(...)"
        )

    def bind(self, *args, **kwargs):
        """DAG/workflow composition (ref: remote_function bind)."""
        from .dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        from . import _worker_api

        refs = _worker_api.core().submit_task(self._function, args, kwargs, self._options)
        num_returns = self._options.get("num_returns", 1)
        if num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        if num_returns == 1:
            return refs[0]
        return refs

    def options(self, **new_options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(new_options)
        return RemoteFunction(self._function, merged)

