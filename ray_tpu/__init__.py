"""ray_tpu: a TPU-native distributed compute framework.

Ray-class capabilities (tasks, actors, distributed objects, lease-based
topology-aware scheduling) re-designed for TPU pods: the device plane is
jax/XLA/pallas over ICI meshes (ray_tpu.parallel, ray_tpu.ops), the host plane
is a shared-memory object store + socket control plane (ray_tpu._private).

Public surface mirrors the reference (ref: python/ray/__init__.py):
    ray_tpu.init / shutdown / remote / get / put / wait / kill / get_actor
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from . import exceptions
from ._private.object_ref import ObjectRef, ObjectRefGenerator
from ._worker_api import (
    available_resources,
    get_tpu_chip_ids,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    wait,
)
from .actor import ActorClass, ActorHandle
from .remote_function import RemoteFunction
from . import util

__version__ = "0.1.0"

_OPTION_KEYS = {
    "num_cpus", "num_tpus", "num_returns", "resources", "max_retries",
    "retry_exceptions", "max_restarts", "max_task_retries", "max_concurrency",
    "name", "namespace", "scheduling_strategy", "runtime_env", "lifetime",
    "placement_group", "placement_group_bundle_index",
    "generator_backpressure_num_objects", "accelerator_type",
    "idempotent", "speculation",
}


def remote(*args, **kwargs):
    """Decorate a function into a RemoteFunction or a class into an ActorClass.

    Usage: @ray_tpu.remote  or  @ray_tpu.remote(num_cpus=2, num_tpus=1)
    """

    def _make(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return _make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    bad = set(kwargs) - _OPTION_KEYS
    if bad:
        raise ValueError(f"Unknown @remote options: {sorted(bad)}")
    return _make


def method(**kwargs):
    """Decorator for actor methods carrying options (ref: ray.method)."""

    def _wrap(fn):
        fn.__ray_tpu_method_options__ = kwargs
        return fn

    return _wrap


__all__ = [
    "ObjectRef", "ObjectRefGenerator", "ActorClass", "ActorHandle",
    "RemoteFunction",
    "init", "shutdown", "is_initialized", "remote", "method",
    "get", "put", "wait", "kill", "cancel", "get_actor",
    "cluster_resources", "available_resources", "nodes",
    "get_tpu_chip_ids",
    "util", "exceptions", "__version__",
]
