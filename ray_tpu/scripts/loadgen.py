"""Traffic-replay load harness for the SLO observability plane.

Open-loop load generation against a serve HTTP proxy: arrivals are
Poisson per tenant (exponential inter-arrival gaps, fired WITHOUT
waiting for responses — a slow server faces a growing backlog exactly
like production traffic, the closed-loop self-throttling artifact the
tail-latency literature warns benchmarks about), prompt/output lengths
are heavy-tailed lognormal, and every request carries its tenant's
``X-Tenant-ID`` so cluster-side metrics partition per tenant.

After the run the harness reads the cluster's SLO plane (util/state
``slo_status`` + ``slo`` cluster events) and writes a JSON report with
client-side latency percentiles per tenant, per-spec SLO attainment,
and the burn-rate alert timeline that fired inside the run window.

Importable (``run_loadgen`` — bench_envelope and obs_smoke drive it
in-process against an initialized cluster) and a standalone CLI::

    python -m ray_tpu.scripts.loadgen --url http://127.0.0.1:8123 \\
        --deployment Echo --tenant acme:8 --tenant free:4 \\
        --duration 30 --report /tmp/slo_report.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class TenantProfile:
    """One tenant's offered load: open-loop Poisson arrivals at
    ``rate_rps``, lognormal prompt/output token lengths (mu/sigma are
    the underlying normal's parameters — sigma ~1 gives the heavy tail
    real prompt-length distributions show)."""
    name: str
    rate_rps: float
    prompt_mu: float = 4.0        # exp(4) ~ 55 tokens median
    prompt_sigma: float = 1.0
    output_mu: float = 3.0        # exp(3) ~ 20 tokens median
    output_sigma: float = 0.7
    max_prompt: int = 4096
    max_output: int = 512

    @classmethod
    def parse(cls, text: str) -> "TenantProfile":
        """CLI shape ``name:rps[:prompt_mu[:prompt_sigma[:out_mu
        [:out_sigma]]]]``."""
        parts = text.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"tenant spec needs name:rps, got {text!r}")
        kwargs: Dict[str, Any] = {"name": parts[0],
                                  "rate_rps": float(parts[1])}
        for key, raw in zip(("prompt_mu", "prompt_sigma",
                             "output_mu", "output_sigma"), parts[2:]):
            kwargs[key] = float(raw)
        return cls(**kwargs)


def echo_payload(rng: random.Random, prompt_len: int,
                 output_len: int) -> dict:
    """Payload for toy (non-LLM) deployments: body size tracks the
    sampled prompt length so transfer cost scales with it."""
    return {"prompt": "x" * prompt_len, "max_tokens": output_len}


def llm_payload(rng: random.Random, prompt_len: int,
                output_len: int) -> dict:
    """OpenAI-completions-shaped payload for LLMServer deployments."""
    return {"prompt_ids": [rng.randrange(1, 1000)
                           for _ in range(max(1, prompt_len))],
            "max_tokens": max(1, output_len)}


_PAYLOADS = {"echo": echo_payload, "llm": llm_payload}


@dataclass
class _TenantStats:
    requests: int = 0
    completed: int = 0
    errors: int = 0
    abandoned: int = 0
    latencies: List[float] = field(default_factory=list)
    prompt_tokens: int = 0
    output_tokens: int = 0


def _pctl(samples: List[float], q: float) -> Optional[float]:
    if not samples:
        return None
    s = sorted(samples)
    return s[min(len(s) - 1, int(q * (len(s) - 1)))]


def _sample_len(rng: random.Random, mu: float, sigma: float,
                cap: int) -> int:
    return max(1, min(cap, int(rng.lognormvariate(mu, sigma))))


async def _drive(url: str, deployment: str,
                 tenants: List[TenantProfile], duration_s: float,
                 payload_fn: Callable[..., dict], seed: int,
                 drain_s: float) -> Dict[str, _TenantStats]:
    import aiohttp

    stats = {t.name: _TenantStats() for t in tenants}
    pending: set = set()
    endpoint = f"{url.rstrip('/')}/{deployment}"

    async with aiohttp.ClientSession() as session:

        async def one(tenant: TenantProfile, rng: random.Random):
            st = stats[tenant.name]
            p_len = _sample_len(rng, tenant.prompt_mu,
                                tenant.prompt_sigma, tenant.max_prompt)
            o_len = _sample_len(rng, tenant.output_mu,
                                tenant.output_sigma, tenant.max_output)
            st.requests += 1
            st.prompt_tokens += p_len
            st.output_tokens += o_len
            t0 = time.monotonic()
            try:
                async with session.post(
                        endpoint,
                        json=payload_fn(rng, p_len, o_len),
                        headers={"X-Tenant-ID": tenant.name,
                                 "X-Request-ID": uuid.uuid4().hex}
                        ) as resp:
                    await resp.read()
                    if resp.status != 200:
                        st.errors += 1
            except asyncio.CancelledError:
                # drain-window straggler: no latency sample — it would
                # record the cancel time, not a service time
                st.abandoned += 1
                raise
            except Exception:  # noqa: BLE001 — client-side failure
                st.errors += 1
            st.latencies.append(time.monotonic() - t0)
            st.completed += 1

        async def tenant_loop(tenant: TenantProfile):
            # per-tenant RNG, string-seeded (deterministic across
            # processes, unlike hash()): arrival process and length
            # draws are reproducible per seed regardless of response
            # timing
            rng = random.Random(f"{seed}:{tenant.name}")
            deadline = time.monotonic() + duration_s
            while time.monotonic() < deadline:
                # open loop: fire and move on — never await the request
                task = asyncio.ensure_future(one(tenant, rng))
                pending.add(task)
                task.add_done_callback(pending.discard)
                gap = rng.expovariate(max(1e-6, tenant.rate_rps))
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                await asyncio.sleep(min(gap, remain))

        await asyncio.gather(*(tenant_loop(t) for t in tenants))
        if pending:
            # bounded drain: in-flight requests get a grace window,
            # stragglers beyond it count as abandoned (never a hang)
            done, still = await asyncio.wait(
                pending, timeout=max(1.0, drain_s))
            for task in still:
                task.cancel()
            if still:
                await asyncio.gather(*still, return_exceptions=True)
    return stats


def _cluster_slo_view(window: tuple) -> Dict[str, Any]:
    """Read the SLO plane from the connected cluster: per-spec status +
    the slo-sourced alert events that fired inside the run window.
    Empty view when no cluster is connected (pure-HTTP runs)."""
    try:
        from ray_tpu.util import state
        status = state.slo_status()
        events = state.list_cluster_events(source="slo", limit=500)
    except Exception:  # noqa: BLE001 — cluster view is optional
        return {"slo": None, "alerts": []}
    t0, t1 = window
    alerts = [
        {"t": e.get("timestamp"), "severity": e.get("severity"),
         "kind": e.get("kind"), "slo": e.get("slo"),
         "message": e.get("message")}
        for e in events
        if t0 - 1.0 <= (e.get("timestamp") or 0) <= t1]
    return {"slo": status, "alerts": alerts}


def run_loadgen(url: str, deployment: str,
                tenants: List[TenantProfile], duration_s: float, *,
                payload: str = "echo",
                payload_fn: Optional[Callable[..., dict]] = None,
                seed: int = 0,
                slo_specs: Optional[List[str]] = None,
                settle_s: float = 5.0,
                drain_s: float = 15.0,
                report_path: Optional[str] = None) -> Dict[str, Any]:
    """Run the open-loop harness and assemble the report.

    With ``slo_specs`` the specs are installed on the connected cluster
    before traffic starts (state.set_slo_specs); ``settle_s`` lets the
    GCS take a couple of evaluation ticks after the run so windowed
    attainment covers the tail of the traffic."""
    if payload_fn is None:
        payload_fn = _PAYLOADS[payload]
    installed = None
    if slo_specs:
        from ray_tpu.util import state
        installed = state.set_slo_specs(slo_specs)
    t0 = time.time()
    loop = asyncio.new_event_loop()
    try:
        stats = loop.run_until_complete(
            _drive(url, deployment, tenants, duration_s, payload_fn,
                   seed, drain_s))
    finally:
        loop.close()
    if settle_s > 0:
        time.sleep(settle_s)
    t1 = time.time()
    view = _cluster_slo_view((t0, t1))
    report: Dict[str, Any] = {
        "url": url, "deployment": deployment, "seed": seed,
        "started_t": t0, "duration_s": duration_s,
        "installed_specs": installed,
        "tenants": {},
        "slo": view["slo"],
        "alerts": view["alerts"],
    }
    for t in tenants:
        st = stats[t.name]
        lat = st.latencies
        report["tenants"][t.name] = {
            "offered_rps": t.rate_rps,
            "requests": st.requests,
            "completed": st.completed,
            "errors": st.errors,
            "abandoned": st.abandoned,
            "achieved_rps": st.completed / max(1e-9, duration_s),
            "prompt_tokens": st.prompt_tokens,
            "output_tokens": st.output_tokens,
            "latency_s": {
                "p50": _pctl(lat, 0.50), "p90": _pctl(lat, 0.90),
                "p95": _pctl(lat, 0.95), "p99": _pctl(lat, 0.99),
                "mean": (sum(lat) / len(lat)) if lat else None,
                "max": max(lat) if lat else None,
            },
        }
    # per-tenant attainment: specs whose selector pins tenant=<name>
    slo = view["slo"] or {}
    per_tenant: Dict[str, list] = {}
    for spec in slo.get("specs", []):
        tenant = (spec.get("selector") or {}).get("tenant")
        key = tenant if tenant else "__all__"
        per_tenant.setdefault(key, []).append({
            "name": spec.get("name"), "spec": spec.get("spec"),
            "attainment": spec.get("attainment"),
            "objective": spec.get("objective"),
            "compliant": spec.get("compliant"),
            "alert": spec.get("alert"),
        })
    report["attainment"] = per_tenant
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2, default=str)
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop multi-tenant load harness (SLO plane)")
    ap.add_argument("--url", required=True,
                    help="serve proxy base url, e.g. http://127.0.0.1:8123")
    ap.add_argument("--deployment", required=True)
    ap.add_argument("--tenant", action="append", required=True,
                    dest="tenants", metavar="NAME:RPS[:MU[:SIGMA...]]",
                    help="repeatable tenant profile "
                         "(name:rps[:prompt_mu[:prompt_sigma"
                         "[:out_mu[:out_sigma]]]])")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--payload", choices=sorted(_PAYLOADS),
                    default="echo")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slo", action="append", default=None,
                    dest="slo_specs",
                    help="repeatable SLO spec to install before the run "
                         "(needs --address)")
    ap.add_argument("--address", default=None,
                    help="GCS address; connect so the report includes "
                         "cluster-side SLO attainment + alerts")
    ap.add_argument("--report", default=None,
                    help="write the JSON report here (also printed)")
    args = ap.parse_args(argv)
    if args.address:
        import ray_tpu
        ray_tpu.init(address=args.address)
    tenants = [TenantProfile.parse(t) for t in args.tenants]
    report = run_loadgen(
        args.url, args.deployment, tenants, args.duration,
        payload=args.payload, seed=args.seed,
        slo_specs=args.slo_specs, report_path=args.report)
    print(json.dumps(report, indent=2, default=str))
    if args.address:
        import ray_tpu
        ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
