"""Observability smoke lane (run by ci.sh): exercise the flight
recorder end to end on a tiny live cluster — task lifecycle transitions
in GCS, Perfetto timeline export with flow events, critical-path
summary, and the serving histograms on the Prometheus scrape."""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

os.environ.setdefault("RAY_TPU_TRACING", "1")

import ray_tpu
from ray_tpu import serve
from ray_tpu.util import state, tracing


def _wait(pred, timeout_s: float, what: str):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


def main() -> int:
    ray_tpu.init(num_cpus=4)
    try:
        # num_cpus=0.5 forces the full lease pipeline (the fastlane
        # shortcut skips the scheduling-phase transitions)
        @ray_tpu.remote(num_cpus=0.5)
        def double(x):
            return x * 2

        assert ray_tpu.get([double.remote(i) for i in range(4)],
                           timeout=60) == [0, 2, 4, 6]

        recorded = _wait(
            lambda: [t for t in state.list_tasks()
                     if len(t.get("state_transitions") or []) >= 3],
            10, "task lifecycle transitions in GCS")
        assert len(recorded) >= 4, f"only {len(recorded)} tasks recorded"

        events = tracing.timeline("/tmp/rtpu_obs_smoke_timeline.json")
        slices = [e for e in events if e.get("ph") == "X"]
        flows = [e for e in events if e.get("ph") in ("s", "f")]
        assert slices, "timeline exported no phase slices"
        assert flows, "timeline exported no flow events"

        summary = state.summarize_tasks(breakdown=True)
        assert summary["tasks_with_transitions"] >= 4, summary
        assert summary["phases"]["execution"] > 0, summary

        @serve.deployment
        class Echo:
            def __call__(self, payload):
                return {"echo": payload}

        serve.run(Echo.bind())
        port = serve.start()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/Echo",
            data=json.dumps("ping").encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 200
        assert resp.headers.get("X-Request-ID"), "proxy minted no request id"
        assert json.loads(resp.read())["result"] == {"echo": "ping"}

        from ray_tpu._private.prometheus import render_cluster

        text = _wait(
            lambda: (lambda t: t if
                     "serve_request_e2e_seconds_bucket" in t else "")(
                         render_cluster()),
            20, "serve histograms on the Prometheus scrape")
        assert "serve_http_request_seconds" in text, text[-2000:]
        assert "serve_replica_queue_depth" in text, text[-2000:]

        serve.shutdown()
        print("observability smoke ok")
        return 0
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
