"""Observability smoke lane (run by ci.sh): exercise the flight
recorder end to end on a tiny live cluster — task lifecycle transitions
in GCS, Perfetto timeline export with flow events, critical-path
summary, the serving histograms on the Prometheus scrape — the stall
sentinel: an injected hang must flag, emit a WARNING event with a
captured stack, and surface through `cli health` / `cli stacks` — the
profiling plane: `cli profile` must name a known-hot function in its
merged folded stacks and `cli memory` must flag a deliberately pinned
ownerless object as a leak suspect — and the SLO plane:
runtime-installed specs must show per-tenant attainment from live
traffic, and an injected slow replica must fire the fast burn-rate
ERROR alert within a couple of evaluation ticks — and the training
goodput plane: a short sharded train run must land a GCS ledger with
goodput < 1.0, nonzero compile badput, `cli train` rendering the
breakdown, and train_step_seconds on the scrape — and the black-box
plane: a kill -9'd worker mid-task must leave a crash bundle that
`cli postmortem` resolves to the dead pid and its in-flight task."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

os.environ.setdefault("RAY_TPU_TRACING", "1")

import ray_tpu
from ray_tpu import serve
from ray_tpu.util import state, tracing


def _wait(pred, timeout_s: float, what: str):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


def _cli(gcs_address: str, *argv: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *argv,
         "--address", gcs_address],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def _stall_sentinel_smoke() -> None:
    """Injected hang -> automatic WARNING event with the worker's stack,
    surfaced end to end through `cli health` and `cli stacks`."""
    @ray_tpu.remote
    def smoke_hang():
        time.sleep(12)
        return "ok"

    ref = smoke_hang.remote()
    stalls = _wait(lambda: state.list_stalls().get("tasks"), 15,
                   "stall sentinel to flag the hung task")
    assert "time.sleep" in stalls[0]["stack"], stalls[0]
    events = _wait(
        lambda: [e for e in state.list_cluster_events(
            source="stall_sentinel", severity="WARNING")
            if e.get("kind") == "task_stall"],
        10, "WARNING cluster event for the stall")
    assert "smoke_hang" in events[-1]["message"], events[-1]
    assert "time.sleep" in events[-1].get("stack", ""), events[-1]

    from ray_tpu import _worker_api

    addr = _worker_api.node().gcs_address
    health = _cli(addr, "health")
    # rc=1 is the health view's "stalls present" signal
    assert health.returncode == 1, (health.returncode, health.stdout,
                                    health.stderr)
    assert "stalled tasks: 1" in health.stdout, health.stdout
    assert "smoke_hang" in health.stdout, health.stdout
    assert "stall_sentinel events" in health.stdout, health.stdout

    stacks = _cli(addr, "stacks")
    assert stacks.returncode == 0, (stacks.returncode, stacks.stderr)
    assert "smoke_hang" in stacks.stdout, stacks.stdout
    assert "time.sleep" in stacks.stdout, stacks.stdout

    assert ray_tpu.get(ref, timeout=60) == "ok"
    _wait(lambda: not state.list_stalls().get("tasks"), 10,
          "stall record to clear after completion")
    health = _cli(addr, "health")
    assert health.returncode == 0, (health.returncode, health.stdout,
                                    health.stderr)
    assert "stalled tasks: 0" in health.stdout, health.stdout


def _slo_smoke() -> None:
    """SLO plane end to end: specs installed at runtime via
    state.set_slo_specs, per-tenant attainment materializing from live
    proxy traffic, then an injected slow replica (the SloSlow failpoint
    set in the environment before ray.init) burning the 200ms p99
    budget at ~100x — the fast burn-rate ERROR event must land within a
    couple of evaluation ticks of the 6s long window filling. Every
    wait here is deadline-bounded: this leg can fail but never hang."""
    @serve.deployment
    class SloEcho:
        def __call__(self, payload):
            return {"ok": True}

    @serve.deployment
    class SloSlow:
        def __call__(self, payload):
            return {"ok": True}

    serve.run(SloEcho.bind(), name="SloEcho")
    serve.run(SloSlow.bind(), name="SloSlow")
    port = serve.start()

    installed = state.set_slo_specs([
        "smoke-latency: latency_p95 < 2s @ tenant=acme window=20s",
        "smoke-slow: latency_p99 < 200ms @ deployment=SloSlow window=20s",
    ])
    assert len(installed) == 2, installed

    def post(name: str, tenant: str):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/{name}",
            data=json.dumps("ping").encode(),
            headers={"Content-Type": "application/json",
                     "X-Tenant-ID": tenant})
        return urllib.request.urlopen(req, timeout=30)

    def _attained():
        for sp in state.slo_status().get("specs", []):
            if sp["name"] == "smoke-latency" \
                    and sp["attainment"] is not None:
                return sp
        return None

    # keep traffic flowing while polling: a windowed delta needs at
    # least two flushed samples of the series, so a one-shot burst that
    # lands inside a single flush tick would never produce attainment
    spec, deadline = None, time.time() + 30
    while time.time() < deadline and spec is None:
        assert post("SloEcho", "acme").status == 200
        time.sleep(0.2)
        spec = _attained()
    assert spec is not None, "per-tenant SLO attainment never appeared"
    assert spec["attainment"] == 1.0, spec
    assert spec["alert"] == "ok", spec
    assert spec["selector"] == {"tenant": "acme"}, spec

    t_inject = time.time()
    deadline = t_inject + 40
    fired = []
    while time.time() < deadline and not fired:
        post("SloSlow", "acme")
        fired = [e for e in state.list_cluster_events(
            source="slo", severity="ERROR")
            if e.get("kind") == "fast_burn"
            and (e.get("timestamp") or 0) >= t_inject]
    assert fired, "fast-burn alert never fired under injected slow"
    # within two 0.5s ticks of the 6s long burn window filling
    assert fired[0]["timestamp"] - t_inject < 15, fired[0]
    assert "smoke-slow" in fired[0]["message"], fired[0]
    serve.shutdown()


def _profile_smoke() -> None:
    """Profiling & memory plane end to end: `cli profile` on the live
    cluster must name a known-hot function in its merged folded stacks
    and write a valid speedscope document; `cli memory` must attribute
    a deliberately pinned ownerless object as a leak suspect; the
    in-process memory_report must attribute a driver-held object."""
    from ray_tpu import _worker_api
    from ray_tpu._private.ids import ObjectID

    addr = _worker_api.node().gcs_address

    @ray_tpu.remote
    def smoke_spin(sec):
        t_end = time.time() + sec
        x = 0
        while time.time() < t_end:
            x += 1
        return x

    ref = smoke_spin.remote(8.0)
    time.sleep(0.5)  # let a worker pick it up
    prof = _cli(addr, "profile", "--duration", "1.5", "--hz", "50",
                "--speedscope", "/tmp/rtpu_obs_smoke_profile.json")
    assert prof.returncode == 0, (prof.returncode, prof.stdout,
                                  prof.stderr)
    assert "smoke_spin" in prof.stdout, prof.stdout
    with open("/tmp/rtpu_obs_smoke_profile.json") as f:
        doc = json.load(f)
    assert doc["profiles"][0]["type"] == "sampled", doc["profiles"][0]
    assert any("smoke_spin" in fr["name"]
               for fr in doc["shared"]["frames"]), \
        "hot function missing from speedscope frames"
    assert ray_tpu.get(ref, timeout=60) > 0

    # memory attribution: a pinned object nobody claims is a leak
    # suspect through `cli memory`; a driver-held ref is attributed
    # local_ref/driver through the in-process report (the CLI is its
    # own driver — it cannot see THIS process's claims)
    core = _worker_api.core()
    leak = ObjectID.from_random()
    core.store.put(leak, b"L" * 8192)  # ownerless: bypasses ref tables
    state._raylet_call(None, "pin_objects", {"object_ids": [leak]})
    held = ray_tpu.put(os.urandom(256 * 1024))
    try:
        mem = _cli(addr, "memory", "--leak-age=-1", "--json")
        assert mem.returncode == 0, (mem.returncode, mem.stdout,
                                     mem.stderr)
        rep = json.loads(mem.stdout)
        suspects = {o["object_id"] for o in rep["leak_suspects"]}
        assert leak.hex() in suspects, rep["leak_suspects"]
        entry = next(o for o in rep["objects"]
                     if o["object_id"] == leak.hex())
        assert entry["ref_type"] == "pinned", entry

        local = state.memory_report()
        mine = next(o for o in local["objects"]
                    if o["object_id"] == held.hex())
        assert mine["ref_type"] == "local_ref", mine
        assert "driver" in mine["owners"], mine
        assert local["cluster"]["attributed_fraction"] > 0, local
    finally:
        state._raylet_call(None, "unpin_objects", {"object_ids": [leak]})
        core.store.delete(leak)
        del held

    # status gains store-utilization columns from the same plane
    status = _cli(addr, "status")
    assert status.returncode == 0, (status.returncode, status.stderr)
    assert "store " in status.stdout, status.stdout


def _train_goodput_smoke() -> None:
    """Training goodput plane end to end: a short sharded train run on
    the tiny Llama config must leave a GCS ledger whose goodput is
    honestly < 1.0 with a nonzero compile badput bucket (the first step
    compiles), `cli train` must render the breakdown, and the
    train_step_seconds phase histograms must reach the Prometheus
    scrape."""
    import dataclasses

    from ray_tpu import _worker_api
    from ray_tpu._private.prometheus import render_cluster
    from ray_tpu.train import RunConfig, ScalingConfig, Trainer

    def train_fn(config):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu import train
        from ray_tpu.models import (
            LLAMA_CONFIGS, init_params, lm_loss, param_logical_axes)
        from ray_tpu.parallel import MeshSpec, build_mesh
        from ray_tpu.train import estimate_flops_per_token, make_train_step

        cfg = LLAMA_CONFIGS["tiny"]
        mesh = build_mesh(MeshSpec(dp=1, fsdp=1, tp=1),
                          jax.devices("cpu")[:1])
        init_fn, step_fn, place_batch = make_train_step(
            lambda p, b: lm_loss(p, b, cfg, mesh=mesh),
            optax.adamw(1e-3), mesh, param_logical_axes(cfg),
            model_flops_per_token=estimate_flops_per_token(
                cfg.n_params()))
        state_ = init_fn(init_params(jax.random.PRNGKey(0), cfg))
        key = jax.random.PRNGKey(1)
        for _step in range(4):
            with train.phase("data_wait"):
                key, sub = jax.random.split(key)
                tokens = jax.random.randint(
                    sub, (4, 32), 0, cfg.vocab, jnp.int32)
            batch = place_batch({"tokens": tokens})
            state_, metrics = step_fn(state_, batch)
            train.report({"loss": float(metrics["loss"])})

    import tempfile

    run_dir = tempfile.mkdtemp(prefix="rtpu_obs_smoke_train_")
    result = Trainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="goodput_smoke",
                             storage_path=run_dir),
    ).fit()
    assert result.error is None, result.error

    jobs = _wait(
        lambda: [dataclasses.asdict(j) if dataclasses.is_dataclass(j)
                 else j for j in state.train_status(
                     job="goodput_smoke").get("jobs", [])
                 if (j.steps if dataclasses.is_dataclass(j)
                     else j.get("steps"))],
        20, "the goodput ledger to fold the step reports")
    job = jobs[0]
    assert job["steps"] >= 3, job
    # honest accounting: compile + data_wait + init all cost something
    assert 0.0 < job["goodput_fraction"] < 1.0, job
    assert job["badput_s"].get("compile", 0.0) > 0.0, job["badput_s"]
    assert job["compile_count"] + job["cache_hit_count"] >= 1, job
    # the >=90% acceptance bar: the ledger named nearly every
    # chip-second it observed
    assert job["attributed_fraction"] >= 0.9, job
    assert job["mfu"] > 0.0, job          # peak flops injected in main()
    assert job["tok_per_s_per_chip"] > 0.0, job

    addr = _worker_api.node().gcs_address
    out = _cli(addr, "train")
    assert out.returncode == 0, (out.returncode, out.stdout, out.stderr)
    assert "goodput_smoke" in out.stdout, out.stdout
    assert "goodput" in out.stdout and "compile" in out.stdout, out.stdout
    as_json = _cli(addr, "train", "--json")
    parsed = json.loads(as_json.stdout)["jobs"]
    assert parsed and parsed[0]["goodput_fraction"] < 1.0, parsed

    _wait(lambda: "train_step_seconds" in render_cluster(), 20,
          "train_step_seconds histograms on the Prometheus scrape")
    scrape = render_cluster()
    assert 'phase="total"' in scrape, "phase label missing from scrape"
    assert "train_goodput_fraction" in scrape, "ledger synthetics missing"


def _postmortem_smoke() -> None:
    """Black-box plane end to end: kill -9 a worker mid-task under
    background traffic; the raylet sweeps the corpse's flight file into
    a crash bundle, `cli postmortem` (file-based — works against dead
    clusters too) must name the dead pid and the in-flight task id, and
    the crash accounting must land on `cli status` + the Prometheus
    scrape."""
    from ray_tpu import _worker_api
    from ray_tpu._private import blackbox
    from ray_tpu._private.prometheus import render_cluster

    session_dir = _worker_api.node().session_dir
    addr = _worker_api.node().gcs_address
    pid_path = os.path.join(session_dir, "postmortem_victim_pid")

    @ray_tpu.remote
    def pm_victim(path):
        with open(path, "w") as f:
            f.write(str(os.getpid()))
        time.sleep(120)

    @ray_tpu.remote
    def pm_background(x):
        time.sleep(0.01)
        return x

    pm_victim.remote(pid_path)
    _wait(lambda: os.path.exists(pid_path), 30, "victim pid file")
    pid = int(open(pid_path).read())
    # background load keeps the rest of the cluster busy mid-incident
    refs = [pm_background.remote(i) for i in range(16)]
    time.sleep(1.0)  # >= one flight flush with the task in flight
    os.kill(pid, signal.SIGKILL)

    bundles = _wait(
        lambda: [b for b in blackbox.read_bundles(session_dir)
                 if b.get("pid") == pid],
        30, "crash bundle for the killed worker")
    task_ids = [r.get("task_id", "") for r in bundles[0]["inflight"]
                if r.get("task_id")]
    assert task_ids, bundles[0]

    pm = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "postmortem",
         "--session", session_dir],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert pm.returncode == 0, (pm.returncode, pm.stdout, pm.stderr)
    assert str(pid) in pm.stdout, pm.stdout
    assert any(t[:12] in pm.stdout for t in task_ids), pm.stdout

    ev = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "events",
         "--session", session_dir, "--severity", "ERROR"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert ev.returncode == 0, (ev.returncode, ev.stdout, ev.stderr)
    assert str(pid) in ev.stdout, ev.stdout

    _wait(lambda: "process_crashes_total" in render_cluster(), 20,
          "crash counter on the Prometheus scrape")
    status = _cli(addr, "status")
    assert status.returncode == 0, (status.returncode, status.stderr)
    assert "process crashes" in status.stdout, status.stdout
    assert ray_tpu.get(refs, timeout=60) == list(range(16))


def main() -> int:
    # the SloSlow failpoint must be in the environment BEFORE ray.init:
    # replica workers read RAY_TPU_FAILPOINTS at spawn (it does not
    # propagate through _system_config); scoped to the SloSlow
    # deployment so every other leg is untouched
    os.environ["RAY_TPU_FAILPOINTS"] = \
        "serve.replica.handle@SloSlow=slow:0.4"
    # fast flight-ring flushes so the postmortem leg's SIGKILL'd worker
    # leaves a fresh corpse (workers read config from env at spawn)
    os.environ["RAY_TPU_BLACKBOX_FLUSH_INTERVAL_S"] = "0.25"
    ray_tpu.init(num_cpus=4, _system_config={
        "blackbox_flush_interval_s": 0.25,
        # tight stall thresholds so the injected hang flags in seconds
        "task_watchdog_interval_s": 0.5,
        "task_stall_threshold_s": 2.0,
        # tight SLO cadence so the slo leg sees series and burn alerts
        # in seconds rather than the production-default minutes
        "metrics_report_interval_ms": 300,
        "metrics_series_min_interval_s": 0.25,
        "slo_eval_interval_s": 0.5,
        "slo_fast_burn_windows_s": "3,6",
        # nominal chip peak so the train leg's MFU is nonzero on CPU
        "train_peak_flops_per_chip": 1e12,
    })
    try:
        # num_cpus=0.5 forces the full lease pipeline (the fastlane
        # shortcut skips the scheduling-phase transitions)
        @ray_tpu.remote(num_cpus=0.5)
        def double(x):
            # measurable execution phase: a microsecond-fast body can
            # collapse RUNNING->OUTPUT_SEALED to 0 and flake the
            # execution>0 assertion below
            time.sleep(0.05)
            return x * 2

        assert ray_tpu.get([double.remote(i) for i in range(4)],
                           timeout=60) == [0, 2, 4, 6]

        # >= 6 means the worker-side transitions (RUNNING/OUTPUT_SEALED/
        # FINISHED) landed too — owner-side records alone satisfy >= 3
        # and would let the summary below run on a partial lifecycle
        recorded = _wait(
            lambda: [t for t in state.list_tasks()
                     if len(t.get("state_transitions") or []) >= 6],
            10, "task lifecycle transitions in GCS")
        assert len(recorded) >= 4, f"only {len(recorded)} tasks recorded"

        events = tracing.timeline("/tmp/rtpu_obs_smoke_timeline.json")
        slices = [e for e in events if e.get("ph") == "X"]
        flows = [e for e in events if e.get("ph") in ("s", "f")]
        assert slices, "timeline exported no phase slices"
        assert flows, "timeline exported no flow events"

        summary = state.summarize_tasks(breakdown=True)
        assert summary["tasks_with_transitions"] >= 4, summary
        assert summary["phases"]["execution"] > 0, summary

        @serve.deployment
        class Echo:
            def __call__(self, payload):
                return {"echo": payload}

        serve.run(Echo.bind())
        port = serve.start()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/Echo",
            data=json.dumps("ping").encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 200
        assert resp.headers.get("X-Request-ID"), "proxy minted no request id"
        assert json.loads(resp.read())["result"] == {"echo": "ping"}

        from ray_tpu._private.prometheus import render_cluster

        # replica- and proxy-side metrics flush on independent ticks:
        # wait for all three, don't assert on whichever landed first
        wanted = ("serve_request_e2e_seconds_bucket",
                  "serve_http_request_seconds",
                  "serve_replica_queue_depth")
        _wait(
            lambda: (lambda t: all(w in t for w in wanted))(
                render_cluster()),
            20, "serve histograms on the Prometheus scrape")

        serve.shutdown()
        _profile_smoke()
        _stall_sentinel_smoke()
        _slo_smoke()
        _train_goodput_smoke()
        _postmortem_smoke()
        print("observability smoke ok")
        return 0
    finally:
        os.environ.pop("RAY_TPU_FAILPOINTS", None)
        os.environ.pop("RAY_TPU_BLACKBOX_FLUSH_INTERVAL_S", None)
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
