"""Observability smoke lane (run by ci.sh): exercise the flight
recorder end to end on a tiny live cluster — task lifecycle transitions
in GCS, Perfetto timeline export with flow events, critical-path
summary, the serving histograms on the Prometheus scrape — and the
stall sentinel: an injected hang must flag, emit a WARNING event with a
captured stack, and surface through `cli health` / `cli stacks`."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

os.environ.setdefault("RAY_TPU_TRACING", "1")

import ray_tpu
from ray_tpu import serve
from ray_tpu.util import state, tracing


def _wait(pred, timeout_s: float, what: str):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


def _cli(gcs_address: str, *argv: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", *argv,
         "--address", gcs_address],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def _stall_sentinel_smoke() -> None:
    """Injected hang -> automatic WARNING event with the worker's stack,
    surfaced end to end through `cli health` and `cli stacks`."""
    @ray_tpu.remote
    def smoke_hang():
        time.sleep(12)
        return "ok"

    ref = smoke_hang.remote()
    stalls = _wait(lambda: state.list_stalls().get("tasks"), 15,
                   "stall sentinel to flag the hung task")
    assert "time.sleep" in stalls[0]["stack"], stalls[0]
    events = _wait(
        lambda: [e for e in state.list_cluster_events(
            source="stall_sentinel", severity="WARNING")
            if e.get("kind") == "task_stall"],
        10, "WARNING cluster event for the stall")
    assert "smoke_hang" in events[-1]["message"], events[-1]
    assert "time.sleep" in events[-1].get("stack", ""), events[-1]

    from ray_tpu import _worker_api

    addr = _worker_api.node().gcs_address
    health = _cli(addr, "health")
    # rc=1 is the health view's "stalls present" signal
    assert health.returncode == 1, (health.returncode, health.stdout,
                                    health.stderr)
    assert "stalled tasks: 1" in health.stdout, health.stdout
    assert "smoke_hang" in health.stdout, health.stdout
    assert "stall_sentinel events" in health.stdout, health.stdout

    stacks = _cli(addr, "stacks")
    assert stacks.returncode == 0, (stacks.returncode, stacks.stderr)
    assert "smoke_hang" in stacks.stdout, stacks.stdout
    assert "time.sleep" in stacks.stdout, stacks.stdout

    assert ray_tpu.get(ref, timeout=60) == "ok"
    _wait(lambda: not state.list_stalls().get("tasks"), 10,
          "stall record to clear after completion")
    health = _cli(addr, "health")
    assert health.returncode == 0, (health.returncode, health.stdout,
                                    health.stderr)
    assert "stalled tasks: 0" in health.stdout, health.stdout


def main() -> int:
    ray_tpu.init(num_cpus=4, _system_config={
        # tight stall thresholds so the injected hang flags in seconds
        "task_watchdog_interval_s": 0.5,
        "task_stall_threshold_s": 2.0,
    })
    try:
        # num_cpus=0.5 forces the full lease pipeline (the fastlane
        # shortcut skips the scheduling-phase transitions)
        @ray_tpu.remote(num_cpus=0.5)
        def double(x):
            # measurable execution phase: a microsecond-fast body can
            # collapse RUNNING->OUTPUT_SEALED to 0 and flake the
            # execution>0 assertion below
            time.sleep(0.05)
            return x * 2

        assert ray_tpu.get([double.remote(i) for i in range(4)],
                           timeout=60) == [0, 2, 4, 6]

        # >= 6 means the worker-side transitions (RUNNING/OUTPUT_SEALED/
        # FINISHED) landed too — owner-side records alone satisfy >= 3
        # and would let the summary below run on a partial lifecycle
        recorded = _wait(
            lambda: [t for t in state.list_tasks()
                     if len(t.get("state_transitions") or []) >= 6],
            10, "task lifecycle transitions in GCS")
        assert len(recorded) >= 4, f"only {len(recorded)} tasks recorded"

        events = tracing.timeline("/tmp/rtpu_obs_smoke_timeline.json")
        slices = [e for e in events if e.get("ph") == "X"]
        flows = [e for e in events if e.get("ph") in ("s", "f")]
        assert slices, "timeline exported no phase slices"
        assert flows, "timeline exported no flow events"

        summary = state.summarize_tasks(breakdown=True)
        assert summary["tasks_with_transitions"] >= 4, summary
        assert summary["phases"]["execution"] > 0, summary

        @serve.deployment
        class Echo:
            def __call__(self, payload):
                return {"echo": payload}

        serve.run(Echo.bind())
        port = serve.start()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/Echo",
            data=json.dumps("ping").encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=30)
        assert resp.status == 200
        assert resp.headers.get("X-Request-ID"), "proxy minted no request id"
        assert json.loads(resp.read())["result"] == {"echo": "ping"}

        from ray_tpu._private.prometheus import render_cluster

        text = _wait(
            lambda: (lambda t: t if
                     "serve_request_e2e_seconds_bucket" in t else "")(
                         render_cluster()),
            20, "serve histograms on the Prometheus scrape")
        assert "serve_http_request_seconds" in text, text[-2000:]
        assert "serve_replica_queue_depth" in text, text[-2000:]

        serve.shutdown()
        _stall_sentinel_smoke()
        print("observability smoke ok")
        return 0
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
