"""ray-tpu CLI (ref: python/ray/scripts/scripts.py — `ray start/stop/
status` + dashboard/modules/job/cli.py — `ray job submit/...`; SURVEY
§1 L8). argparse instead of click; same verbs.

    python -m ray_tpu.scripts.cli start --head --port 6380
    python -m ray_tpu.scripts.cli start --address HOST:PORT
    python -m ray_tpu.scripts.cli status [--address ...]
    python -m ray_tpu.scripts.cli stop
    python -m ray_tpu.scripts.cli job submit [--address ...] -- CMD...
    python -m ray_tpu.scripts.cli job {list,status,logs,stop} ...
    python -m ray_tpu.scripts.cli state {nodes,actors,tasks,objects}
    python -m ray_tpu.scripts.cli health [--verbose]
    python -m ray_tpu.scripts.cli stacks [--node PREFIX] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional

_RUN_DIR = "/tmp/ray_tpu"
_ADDR_FILE = os.path.join(_RUN_DIR, "current_address")


def _write_runfile(address: str, pid: int) -> None:
    os.makedirs(_RUN_DIR, exist_ok=True)
    with open(_ADDR_FILE, "w") as f:
        json.dump({"address": address, "pid": pid}, f)


def _read_runfile() -> Optional[dict]:
    try:
        with open(_ADDR_FILE) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _resolve_address(args) -> str:
    if getattr(args, "address", None):
        return args.address
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    run = _read_runfile()
    if run:
        return run["address"]
    raise SystemExit("no cluster address: pass --address, set "
                     "RAY_TPU_ADDRESS, or `start --head` on this host")


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return f"{int(n)}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TiB"


# ------------------------------------------------------------------ start

def cmd_kv_server(args) -> int:
    import asyncio

    from ray_tpu._private.kv_server import _amain

    try:
        asyncio.run(_amain(args.address, args.data))
    except KeyboardInterrupt:  # graftlint: ignore[swallow] — quiet ^C exit
        pass
    return 0


def cmd_start(args) -> int:
    if args.block:
        return _start_blocking(args)
    # detach: re-exec ourselves with --block in a new session, wait for
    # the address file (ref: `ray start` daemonization)
    os.makedirs(_RUN_DIR, exist_ok=True)
    if os.path.exists(_ADDR_FILE):
        os.unlink(_ADDR_FILE)
    cmd = [sys.executable, "-m", "ray_tpu.scripts.cli", "start", "--block"]
    for flag in ("head",):
        if getattr(args, flag):
            cmd.append(f"--{flag}")
    if args.address:
        cmd += ["--address", args.address]
    if args.port is not None:
        cmd += ["--port", str(args.port)]
    if args.num_cpus is not None:
        cmd += ["--num-cpus", str(args.num_cpus)]
    if args.object_store_memory is not None:
        cmd += ["--object-store-memory", str(args.object_store_memory)]
    if getattr(args, "external_store", None):
        cmd += ["--external-store", args.external_store]
    proc = subprocess.Popen(cmd, start_new_session=True,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.time() + 60
    while time.time() < deadline:
        run = _read_runfile()
        if run and run.get("pid") == proc.pid:
            print(f"started: {run['address']} (pid {proc.pid})")
            if args.head:
                print(f"join workers with:\n  python -m ray_tpu.scripts.cli "
                      f"start --address {run['address']}")
            return 0
        if proc.poll() is not None:
            raise SystemExit(f"node process exited rc={proc.returncode}")
        time.sleep(0.1)
    raise SystemExit("timed out waiting for the node to come up")


def _start_blocking(args) -> int:
    from ray_tpu._private.node import Node, default_resources

    resources = default_resources()
    if args.num_cpus is not None:
        resources["CPU"] = float(args.num_cpus)
    if args.head:
        node = Node(head=True, port=args.port if args.port is not None else 0,
                    resources=resources, node_ip=args.node_ip,
                    object_store_memory=args.object_store_memory,
                    external_store_address=getattr(args, "external_store",
                                                   None))
    else:
        if not args.address:
            raise SystemExit("worker start needs --address HOST:PORT")
        # session name rides the GCS KV (written at head start)
        from ray_tpu._private.rpc import RpcClient

        client = RpcClient(args.address)
        import asyncio

        async def _session():
            await client.connect()
            raw = await client.call(
                "kv_get", {"ns": "cluster", "key": "session_name"})
            await client.close()
            if raw is None:
                raise SystemExit(f"no cluster at {args.address}")
            return raw.decode()

        session = asyncio.run(_session())
        node = Node(head=False, session_name=session,
                    gcs_address=args.address, resources=resources,
                    node_ip=args.node_ip,
                    object_store_memory=args.object_store_memory)
    node.start()
    address = node.gcs_address if args.head else args.address
    if args.head and address.startswith("0.0.0.0"):
        address = f"{node.node_ip}:{address.rsplit(':', 1)[1]}"
    _write_runfile(address, os.getpid())
    print(f"node up: {address}", flush=True)
    stop = {"flag": False}

    def _sig(*_):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    while not stop["flag"]:
        time.sleep(0.2)
    # bounded teardown: a wedged component must not keep a SIGTERM'd
    # daemon alive forever (observed: heads surviving `stop` for hours)
    import threading

    killer = threading.Timer(20.0, lambda: os._exit(1))
    killer.daemon = True
    killer.start()
    node.stop()
    killer.cancel()
    return 0


def _local_node_pids() -> list:
    """Every `cli start --block` node process on this host (the
    reference `ray stop` contract: stop ALL local nodes, not just the
    last runfile writer — a worker join overwrites the runfile and
    would otherwise strand the head forever). Matches on parsed argv
    tokens, so `bash -c "... start --block ..."` wrapper shells and
    grep bystanders (where the tokens sit inside ONE argv string) are
    never swept."""
    pids = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit() or int(entry) == os.getpid():
            continue
        try:
            with open(f"/proc/{entry}/cmdline", "rb") as f:
                argv = f.read().decode(errors="replace").split("\0")
        except OSError:
            continue
        if ("ray_tpu.scripts.cli" in argv and "start" in argv
                and "--block" in argv):
            pids.append(int(entry))
    return pids


def cmd_stop(args) -> int:
    pids = _local_node_pids()
    run = _read_runfile()
    if run and run["pid"] not in pids:
        # a runfile pid NOT matching the node-argv scan is stale (node
        # died, pid possibly recycled by an unrelated process): never
        # signal it — the scan is the verification
        print(f"runfile pid {run['pid']} is not a node process "
              f"(stale runfile)")
    if not pids:
        if not run:
            print("no tracked node on this host")
        try:
            os.unlink(_ADDR_FILE)
        except OSError:
            pass
        return 0
    # signal ALL nodes first, then poll them under one shared deadline,
    # then SIGKILL survivors — N wedged nodes cost one grace window,
    # not N of them
    for pid in pids:
        try:
            os.kill(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.time() + 25
    remaining = set(pids)
    while remaining and time.time() < deadline:
        for pid in list(remaining):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                remaining.discard(pid)
                print(f"stopped pid {pid}")
            except PermissionError:
                remaining.discard(pid)  # another user's node: not ours
        time.sleep(0.1)
    for pid in remaining:
        try:
            os.kill(pid, signal.SIGKILL)
            print(f"killed pid {pid} (graceful stop timed out)")
        except (ProcessLookupError, PermissionError):
            pass
    try:
        os.unlink(_ADDR_FILE)
    except OSError:
        pass
    return 0


# ------------------------------------------------------------------ status

def cmd_status(args) -> int:
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    nodes = state_api.list_nodes()
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    # per-node object-store + HBM columns (profiling & memory plane):
    # store figures come from each raylet's node_stats; HBM from the
    # hbm_* gauges workers publish off the stall-probe tick
    store_cols = {}
    for n in nodes:
        if n["state"] != "ALIVE":
            continue
        try:
            st = state_api._raylet_call(n["node_id"], "node_stats", {})
            store_cols[n["node_id"]] = (
                st.get("store_used_bytes", 0),
                st.get("store_capacity_bytes", 0),
                st.get("num_objects", 0))
        except Exception:  # graftlint: ignore[swallow] — one dead
            continue       # raylet must not blank the whole status table
    hbm_cols: dict = {}
    try:
        rows = (state_api.get_metrics("hbm_bytes_in_use")
                + state_api.get_metrics("hbm_bytes_limit"))
    except Exception:  # noqa: BLE001 — metrics plane is optional here
        rows = []
    for e in rows:
        node_tag = (e.get("tags") or {}).get("node", "")
        use, lim, ndev = hbm_cols.get(node_tag, (0, 0, 0))
        if e["name"] == "hbm_bytes_in_use":
            hbm_cols[node_tag] = (use + e.get("value", 0), lim, ndev + 1)
        else:
            hbm_cols[node_tag] = (use, lim + e.get("value", 0), ndev)
    print(f"nodes: {len(nodes)}")
    for n in nodes:
        hb = n.get("heartbeat_age_s")
        hb_s = f"hb {hb:.1f}s ago" if hb is not None else "hb never"
        off = n.get("clock_offset") or 0.0
        store_s = ""
        if n["node_id"] in store_cols:
            used, cap, nobj = store_cols[n["node_id"]]
            pct = 100.0 * used / cap if cap else 0.0
            store_s = (f"  store {_fmt_bytes(used)}/{_fmt_bytes(cap)}"
                       f" ({pct:.0f}%, {nobj} obj)")
        hbm_s = ""
        if n["node_id"][:12] in hbm_cols:
            use, lim, ndev = hbm_cols[n["node_id"][:12]]
            hbm_s = (f"  hbm {_fmt_bytes(use)}/{_fmt_bytes(lim)}"
                     f" on {ndev} chip(s)")
        print(f"  {n['node_id'][:16]}  {n['state']:5s}  {hb_s:14s}  "
              f"clock {off:+.4f}s  {n['resources_total']}"
              f"{store_s}{hbm_s}")
    print("resources:")
    for key in sorted(total):
        print(f"  {key}: {avail.get(key, 0):g}/{total[key]:g} available")
    # black-box plane liveness: per-process uptime + crash counters
    # (gcs._process_metrics synthesizes these into the metrics pipeline)
    try:
        up_rows = state_api.get_metrics("process_uptime_seconds")
        crash_rows = state_api.get_metrics("process_crashes_total")
    except Exception:  # noqa: BLE001 — metrics plane is optional here
        up_rows, crash_rows = [], []
    if up_rows:
        print("process uptime:")
        for e in sorted(up_rows, key=lambda r: sorted(
                (r.get("tags") or {}).items())):
            tags = e.get("tags") or {}
            v = e.get("value", 0.0)
            up_s = (f"{v / 3600:.1f}h" if v >= 3600
                    else f"{v / 60:.1f}m" if v >= 60 else f"{v:.0f}s")
            print(f"  {tags.get('role', '?'):7s} "
                  f"{tags.get('node', '?'):12s} up {up_s}")
    if crash_rows:
        print("process crashes:")
        for e in crash_rows:
            tags = e.get("tags") or {}
            sig = tags.get("signal") or "-"
            print(f"  {tags.get('role', '?'):7s} "
                  f"{tags.get('node', '?'):12s} "
                  f"{tags.get('reason', '?')} (signal {sig}): "
                  f"{e.get('value', 0):g}")
    _print_serve_status()
    ray_tpu.shutdown()
    return 0


def _print_serve_status() -> None:
    """Serve deployments + fleet-KV routing counters, shown only when a
    serve controller is already running (status must never create one)."""
    import ray_tpu
    from ray_tpu.serve.controller import CONTROLLER_NAME
    from ray_tpu.util import state as state_api

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return  # no serve controller: nothing to show
    try:
        deployments = ray_tpu.get(
            controller.list_deployments.remote(), timeout=15)
    except Exception as exc:  # graftlint: ignore[swallow] — `status`
        # is a diagnostic surface: a dead controller is REPORTED on
        # stdout (with the cause) and must not crash the whole command
        print(f"serve: controller unreachable ({exc})")
        return
    if not deployments:
        return
    print("serve deployments:")
    for d in deployments:
        pools = d.get("pools")
        pool_s = ("  pools " + " ".join(f"{p}={n}"
                                        for p, n in sorted(pools.items()))
                  if pools else "")
        summ = d.get("prefix_summaries")
        summ_s = f"  prefix-summaries {summ}" if summ else ""
        print(f"  {d['name']:20s} replicas "
              f"{d['num_replicas']}/{d['target_replicas']}{pool_s}{summ_s}")
    rows = []
    try:
        for name in ("serve_prefix_route_hits", "serve_prefix_route_misses",
                     "serve_kv_handoff_bytes_total",
                     "serve_kv_handoff_retries_total"):
            rows.extend(state_api.get_metrics(name))
    except Exception:  # noqa: BLE001 — metrics plane is optional here
        rows = []
    if rows:
        print("fleet KV routing:")
        for e in rows:
            tags = e.get("tags") or {}
            tag_s = " ".join(f"{k}={v}" for k, v in sorted(tags.items()))
            print(f"  {e['name']:32s} {e.get('value', 0):g}  {tag_s}")
    spec_rows = []
    try:
        for name in ("llm_spec_draft_tokens_total",
                     "llm_spec_accepted_tokens_total",
                     "llm_spec_acceptance_ratio"):
            spec_rows.extend(state_api.get_metrics(name))
    except Exception:  # noqa: BLE001 — metrics plane is optional here
        spec_rows = []
    if spec_rows:
        print("speculative decoding:")
        for e in spec_rows:
            tags = e.get("tags") or {}
            tag_s = " ".join(f"{k}={v}" for k, v in sorted(tags.items()))
            print(f"  {e['name']:32s} {e.get('value', 0):g}  {tag_s}")


def cmd_health(args) -> int:
    """Stall-sentinel view: stalled tasks / transfers / hung collectives
    with captured stacks, per-host straggler scores, and recent
    stall_sentinel WARNING events."""
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    stalls = state_api.list_stalls()
    tasks = stalls.get("tasks", [])
    transfers = stalls.get("transfers", [])
    collectives = stalls.get("collectives", [])
    rc = 0
    print(f"stalled tasks: {len(tasks)}")
    for s in tasks:
        print(f"  task {s['task_id'][:16]} ({s.get('fn', '?')}) RUNNING "
              f"{s.get('age_s', 0):.1f}s (threshold "
              f"{s.get('threshold_s', 0):.1f}s) on node "
              f"{s.get('node_id', '')[:12]} pid {s.get('pid')}")
        if args.verbose and s.get("stack"):
            print("    " + s["stack"].replace("\n", "\n    "))
    print(f"stalled transfers: {len(transfers)}")
    for s in transfers:
        print(f"  pull {s['object_id'][:16]} on node "
              f"{s.get('node_id', '')[:12]}: no progress for "
              f"{s.get('stalled_for_s', 0):.1f}s "
              f"({s.get('watermark', 0)}/{s.get('size', 0)} bytes)")
    print(f"hung collectives: {len(collectives)}")
    for s in collectives:
        print(f"  {s.get('group')} step {s.get('step')} ({s.get('op')}): "
              f"missing ranks {s.get('missing_ranks')} of "
              f"{s.get('size')}")
    if tasks or transfers or collectives:
        rc = 1
    scores = state_api.straggler_scores()
    if scores:
        print("straggler scores (ema lateness / cluster mean):")
        for s in scores:
            print(f"  {s['host']:24s} score {s.get('score', 0):6.2f}  "
                  f"ema {s.get('ema_lateness_s', 0):.4f}s  worst in "
                  f"{s.get('worst_count', 0)}/{s.get('steps', 0)} step(s)")
    events = state_api.list_cluster_events(source="stall_sentinel",
                                           limit=args.events)
    print(f"recent stall_sentinel events: {len(events)}")
    for e in events:
        print(f"  [{e.get('severity')}] {e.get('message')}")
    ray_tpu.shutdown()
    return rc


def cmd_slo(args) -> int:
    """SLO plane view: per-spec attainment, burn rates, alert state and
    recent burn-rate alert events. rc=1 when any alert is firing."""
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    status = state_api.slo_status()
    rc = 0
    if not status.get("enabled"):
        print("SLO monitor disabled (metrics_series_enabled=False or "
              "slo_eval_interval_s=0)")
        ray_tpu.shutdown()
        return 0
    if args.json:
        print(json.dumps(status, default=str))
        ray_tpu.shutdown()
        return 1 if any(s.get("alert") != "ok"
                        for s in status.get("specs", [])) else 0
    specs = status.get("specs", [])
    print(f"SLO specs: {len(specs)}")
    for s in specs:
        att = s.get("attainment")
        att_s = "-" if att is None else f"{att * 100:.3f}%"
        ach = s.get("achieved")
        ach_s = "" if ach is None else f"  achieved {ach * 1000:.1f}ms"
        alert = s.get("alert", "ok")
        if alert != "ok":
            rc = 1
        burns = s.get("burns") or {}
        burn_s = " ".join(
            f"{k}={v.get('short', 0):g}x/{v.get('long', 0):g}x"
            for k, v in sorted(burns.items()))
        mark = {"ok": " ", "slow_burn": "!", "fast_burn": "!!"}.get(
            alert, "?")
        print(f"  [{mark:2s}] {s.get('spec')}")
        print(f"       attainment {att_s} (objective "
              f"{s.get('objective', 0) * 100:g}%){ach_s}  "
              f"events {s.get('total', 0):g}  alert {alert}  {burn_s}")
        if args.history:
            for h in s.get("history", [])[-args.history:]:
                h_att = h.get("attainment")
                h_s = "-" if h_att is None else f"{h_att * 100:.2f}%"
                print(f"       t={h.get('t', 0):.1f} attainment {h_s} "
                      f"alert {h.get('alert')}")
    events = state_api.list_cluster_events(source="slo",
                                           limit=args.events)
    print(f"recent slo events: {len(events)}")
    for e in events:
        print(f"  [{e.get('severity')}] {e.get('message')}")
    ray_tpu.shutdown()
    return rc


def cmd_train(args) -> int:
    """Training goodput view: per-job goodput %, badput breakdown by
    cause, MFU, tok/s/chip, compile counts, and a per-host straggler
    skew heatmap from the GCS goodput ledger."""
    import dataclasses

    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    status = state_api.train_status(job=args.job)
    jobs = [dataclasses.asdict(j) if dataclasses.is_dataclass(j) else j
            for j in status.get("jobs", [])]
    if args.json:
        print(json.dumps({"jobs": jobs}, default=str))
        ray_tpu.shutdown()
        return 0
    if not jobs:
        print("no training jobs reporting goodput telemetry")
        ray_tpu.shutdown()
        return 0
    for j in jobs:
        good = j.get("goodput_fraction", 0.0) or 0.0
        attr = j.get("attributed_fraction", 0.0) or 0.0
        print(f"job {j.get('job')}  (world {j.get('world_size')}, "
              f"{j.get('chips')} chip(s), {j.get('steps')} step(s), "
              f"{j.get('restarts', 0)} restart(s))")
        print(f"  goodput {good * 100:.1f}%   attributed "
              f"{attr * 100:.1f}% of chip-seconds")
        mfu = j.get("mfu", 0.0)
        tps = j.get("tok_per_s_per_chip", 0.0)
        perf = []
        if mfu:
            perf.append(f"MFU {mfu * 100:.1f}%")
        if tps:
            perf.append(f"{tps:,.0f} tok/s/chip")
        if perf:
            print("  " + "   ".join(perf))
        print(f"  compiles: {j.get('compile_count', 0)} cold, "
              f"{j.get('cache_hit_count', 0)} cache-hit, "
              f"{j.get('recompile_count', 0)} recompile(s); "
              f"rework {j.get('rework_steps', 0)} step(s)")
        badput = j.get("badput_s") or {}
        total_bad = sum(badput.values())
        prod = j.get("productive_s", 0.0)
        if badput:
            print(f"  badput breakdown ({total_bad:.2f} chip-s bad vs "
                  f"{prod:.2f} productive):")
            for cause, secs in sorted(badput.items(),
                                      key=lambda kv: -kv[1]):
                frac = secs / total_bad if total_bad > 0 else 0.0
                bar = "#" * max(1, int(round(frac * 30)))
                print(f"    {cause:12s} {secs:10.3f}s  {frac * 100:5.1f}%"
                      f"  {bar}")
        skew = j.get("rank_skew") or {}
        if skew:
            worst = max(skew.values()) or 1e-9
            print("  per-rank skew (ema seconds waiting on gang):")
            for who, secs in sorted(skew.items(),
                                    key=lambda kv: -kv[1]):
                bar = "#" * max(0, int(round(secs / worst * 20)))
                print(f"    {who:24s} {secs:8.4f}s  {bar}")
        recent = (j.get("recent") or [])[-args.steps:] if args.steps else []
        for r in recent:
            ph = r.get("phases") or {}
            ph_s = " ".join(f"{k}={v:.3f}" for k, v in sorted(ph.items()))
            print(f"    step {r.get('step')}: wall {r.get('wall_s', 0):.3f}s"
                  f"  mfu {(r.get('mfu') or 0) * 100:.1f}%  {ph_s}")
    ray_tpu.shutdown()
    return 0


def cmd_stacks(args) -> int:
    """Live Python stacks of every worker in the cluster (or one node
    with --node), annotated with running task ids and time-in-state —
    `py-spy dump` for the whole cluster, over the control plane."""
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    dumps = state_api.dump_stacks(node_id=args.node)
    if args.json:
        print(json.dumps(dumps, default=str))
        ray_tpu.shutdown()
        return 0
    for node in dumps:
        print(f"node {node.get('node_id', '')[:16]}: "
              f"{len(node.get('workers', []))} worker(s)")
        if node.get("error"):
            print(f"  <error: {node['error']}>")
        for w in node.get("workers", []):
            if w.get("error"):
                print(f"  worker pid {w.get('pid')}: <error: {w['error']}>")
                continue
            print(f"  worker pid {w.get('pid')} "
                  f"({w.get('worker_id', '')[:12]})")
            for th in w.get("threads", []):
                task = th.get("task_id")
                tag = (f" task {task[:16]} ({th.get('fn', '?')}) running "
                       f"{th.get('running_for_s', 0):.1f}s" if task else "")
                print(f"    thread {th.get('name')}{tag}")
                stack = th.get("stack", "")
                print("      " + stack.rstrip().replace("\n", "\n      "))
    ray_tpu.shutdown()
    return 0


def cmd_profile(args) -> int:
    """Cluster flamegraph (ref: Google-Wide Profiling): sample every
    worker's stacks for --duration at --hz, merge the folded stacks on
    the GCS, and print/export the result (collapsed-stack text for
    flamegraph.pl, speedscope JSON for speedscope.app)."""
    import ray_tpu
    from ray_tpu.util import stacks
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    prof = state_api.profile_cluster(
        duration_s=args.duration, hz=args.hz, node_id=args.node)
    folded = prof.get("cpu" if args.cpu else "wall", {}) or {}
    if args.deployment:
        # keep samples whose annotation root names the deployment
        # (task-executing threads are rooted ``task:<fn>``)
        folded = {k: v for k, v in folded.items()
                  if args.deployment in k.split(";", 1)[0]}
    if args.json:
        print(json.dumps(prof, default=str))
        ray_tpu.shutdown()
        return 0
    if args.output:
        with open(args.output, "w") as f:
            f.write(stacks.collapse_lines(folded) + "\n")
        print(f"wrote {len(folded)} folded stacks to {args.output}")
    if args.speedscope:
        doc = stacks.speedscope(
            folded, name=f"ray_tpu {'cpu' if args.cpu else 'wall'} "
                         f"profile", hz=prof.get("hz", args.hz))
        with open(args.speedscope, "w") as f:
            json.dump(doc, f)
        print(f"wrote speedscope profile to {args.speedscope} "
              f"(open at https://www.speedscope.app)")
    view = "cpu" if args.cpu else "wall"
    print(f"profiled {prof.get('workers', 0)} worker(s): "
          f"{prof.get('samples', 0)} samples over "
          f"{prof.get('duration_s', 0.0):.1f}s @ "
          f"{prof.get('hz', 0.0):g} Hz ({view} view)")
    by_class = prof.get("by_class", {})
    if by_class:
        total = sum(by_class.values()) or 1
        print("by scheduling class:")
        for cls, n in sorted(by_class.items(), key=lambda kv: -kv[1]):
            print(f"  {cls:40s} {n:8.0f}  {100.0 * n / total:5.1f}%")
    rows = sorted(folded.items(), key=lambda kv: (-kv[1], kv[0]))
    if rows:
        print(f"top {min(args.top, len(rows))} stacks:")
        for key, n in rows[:args.top]:
            print(f"  {int(n):6d}  {key}")
    for err in prof.get("errors", []):
        print(f"  <error: {err}>")
    ray_tpu.shutdown()
    return 0


# ------------------------------------------------------------------ jobs

def cmd_job(args) -> int:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    if args.job_cmd == "submit":
        entrypoint = " ".join(args.entrypoint)
        runtime_env = None
        if args.env_json:
            runtime_env = json.loads(args.env_json)
        sid = client.submit_job(entrypoint=entrypoint,
                                submission_id=args.submission_id,
                                runtime_env=runtime_env)
        print(sid)
        if args.follow:
            for chunk in client.tail_job_logs(sid):
                sys.stdout.write(chunk)
                sys.stdout.flush()
            status = client.get_job_status(sid)
            print(f"\njob {sid}: {status}")
            return 0 if status == "SUCCEEDED" else 1
    elif args.job_cmd == "list":
        for info in client.list_jobs():
            print(f"{info.submission_id}  {info.status:9s}  "
                  f"{info.entrypoint}")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.submission_id))
    elif args.job_cmd == "stop":
        client.stop_job(args.submission_id)
        print("stopped")
    import ray_tpu

    ray_tpu.shutdown()
    return 0


# ------------------------------------------------------------------ state

def cmd_state(args) -> int:
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    fn = {
        "nodes": state_api.list_nodes,
        "actors": state_api.list_actors,
        "tasks": state_api.list_tasks,
        "objects": state_api.list_objects,
    }[args.kind]
    for row in fn():
        print(json.dumps(row, default=str))
    ray_tpu.shutdown()
    return 0


def cmd_timeline(args) -> int:
    """Dump task events as a chrome://tracing JSON file (ref:
    `ray timeline`; open in Perfetto)."""
    import ray_tpu
    from ray_tpu.util import tracing

    ray_tpu.init(address=_resolve_address(args))
    events = tracing.timeline(args.output)
    print(f"wrote {len(events)} events to {args.output}")
    ray_tpu.shutdown()
    return 0


def cmd_summary(args) -> int:
    """Critical-path report: cluster task wall time attributed to
    scheduling / dep-fetch / execution / transfer (from the flight
    recorder's clock-corrected state transitions)."""
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    report = state_api.summarize_tasks(breakdown=True)
    print("tasks by state:")
    for st in sorted(report["states"]):
        print(f"  {st:24s} {report['states'][st]}")
    phases = report["phases"]
    total = sum(phases.values())
    print(f"phase breakdown ({report['tasks_with_transitions']} task(s) "
          f"with transitions, {report['wall_time_s']:.3f}s wall):")
    for ph in ("scheduling", "dep_fetch", "execution", "transfer", "other"):
        v = phases.get(ph, 0.0)
        pct = 100.0 * v / total if total > 0 else 0.0
        print(f"  {ph:12s} {v:10.3f}s  {pct:5.1f}%")
    ray_tpu.shutdown()
    return 0


def cmd_memory(args) -> int:
    """Memory attribution (ref: `ray memory` — the leak-hunting view):
    object-store bytes per node broken down by ref-type (who is keeping
    each byte alive), leak suspects, per-worker heap, per-chip HBM."""
    import ray_tpu
    from ray_tpu.util import state as state_api

    ray_tpu.init(address=_resolve_address(args))
    rep = state_api.memory_report(leak_age_s=args.leak_age,
                                  limit=args.top)
    if args.json:
        print(json.dumps(rep, default=str))
        ray_tpu.shutdown()
        return 0
    cl = rep.get("cluster", {})
    used = cl.get("used_bytes", 0)
    print(f"object store: {_fmt_bytes(used)} live + "
          f"{_fmt_bytes(cl.get('spill_bytes', 0))} spilled in "
          f"{cl.get('num_objects', 0)} object(s); "
          f"{100.0 * cl.get('attributed_fraction', 0.0):.1f}% "
          f"attributed to a holder")
    by_type = cl.get("by_ref_type", {})
    if by_type:
        print("by ref-type:")
        for t, b in sorted(by_type.items(), key=lambda kv: -kv[1]):
            pct = 100.0 * b / used if used else 0.0
            print(f"  {t:18s} {_fmt_bytes(b):>10s}  {pct:5.1f}%")
    print("nodes:")
    for nd in rep.get("nodes", []):
        cap = nd.get("capacity_bytes", 0)
        pct = 100.0 * nd.get("used_bytes", 0) / cap if cap else 0.0
        print(f"  {nd['node_id'][:16]}  "
              f"{_fmt_bytes(nd.get('used_bytes', 0))}/"
              f"{_fmt_bytes(cap)} ({pct:.0f}%)  "
              f"{nd.get('num_objects', 0)} obj  "
              f"spill {_fmt_bytes(nd.get('spill_bytes', 0))}")
    suspects = rep.get("leak_suspects", [])
    if suspects:
        print(f"leak suspects ({len(suspects)}; pinned, unclaimed, "
              f"old):")
        for o in suspects:
            print(f"  {o['object_id'][:16]}  "
                  f"{_fmt_bytes(o['size']):>10s}  pinned x{o['pinned']}"
                  f"  age {o['age_s']:.0f}s  node {o['node_id'][:12]}")
    objs = rep.get("objects", [])
    if objs and args.verbose:
        print(f"top {min(args.top, len(objs))} objects:")
        for o in objs[:args.top]:
            owners = ",".join(o.get("owners", [])) or "-"
            print(f"  {o['object_id'][:16]}  "
                  f"{_fmt_bytes(o['size']):>10s}  {o['ref_type']:16s}  "
                  f"age {o['age_s']:6.0f}s  owner {owners}")
    workers = rep.get("workers", [])
    if workers:
        print("worker heap:")
        for w in workers:
            heap = w.get("heap", {})
            cur = heap.get("current_bytes", 0)
            peak = heap.get("peak_bytes")
            peak_s = (f" (peak {_fmt_bytes(peak)})"
                      if peak is not None else "")
            hbm = w.get("hbm", [])
            hbm_s = ""
            if hbm:
                hbm_use = sum(d.get("bytes_in_use", 0) for d in hbm)
                hbm_s = (f"  hbm {_fmt_bytes(hbm_use)} on "
                         f"{len(hbm)} chip(s)")
            print(f"  pid {w.get('pid')} ({w.get('mode', '?'):8s}) "
                  f"{heap.get('kind', '?'):11s} "
                  f"{_fmt_bytes(cur):>10s}{peak_s}"
                  f"  inflight {w.get('num_inflight_tasks', 0)}{hbm_s}")
    for err in rep.get("errors", []):
        print(f"  <error: {err}>")
    ray_tpu.shutdown()
    return 0


# ------------------------------------------------------- black-box plane

def _resolve_session_dir(args) -> str:
    """A session dir for the offline black-box readers: --session wins;
    otherwise the most recently touched rtpu_* dir under /tmp/ray_tpu
    (a cleanly stopped head removes its dir, so what survives is the
    crashed/running session the postmortem wants)."""
    explicit = getattr(args, "session", None)
    if explicit:
        path = (explicit if os.path.isdir(explicit)
                else os.path.join(_RUN_DIR, explicit))
        if not os.path.isdir(path):
            raise SystemExit(f"no session dir at {explicit!r}")
        return path
    try:
        cands = [os.path.join(_RUN_DIR, d) for d in os.listdir(_RUN_DIR)
                 if d.startswith("rtpu_")
                 and os.path.isdir(os.path.join(_RUN_DIR, d))]
    except OSError:
        cands = []
    if not cands:
        raise SystemExit(f"no rtpu_* session dirs under {_RUN_DIR}; "
                         "pass --session PATH")
    return max(cands, key=os.path.getmtime)


def cmd_events(args) -> int:
    """Cluster event stream from the PERSISTED journal
    (<session>/blackbox/events.jsonl) — works against a dead cluster,
    and --follow tails it live like `tail -f`."""
    from ray_tpu._private import blackbox

    session_dir = _resolve_session_dir(args)
    path = blackbox.events_journal_path(session_dir)

    def _emit(rec: dict) -> None:
        t = rec.get("timestamp") or 0.0
        ts = time.strftime("%H:%M:%S", time.localtime(t)) if t else "--"
        print(f"{ts} [{rec.get('severity', '?'):7s}] "
              f"[{rec.get('source', '?')}] {rec.get('message', '')}",
              flush=True)

    def _match(rec: dict) -> bool:
        if args.severity and rec.get("severity") != args.severity:
            return False
        if args.source and rec.get("source") != args.source:
            return False
        return True

    recs = blackbox.read_events_journal(
        session_dir, severity=args.severity, source=args.source,
        limit=args.limit)
    if not recs and not args.follow and not os.path.exists(path):
        print(f"no event journal at {path} "
              "(event_journal_enabled off, or the session never started)")
        return 1
    for rec in recs:
        _emit(rec)
    if not args.follow:
        return 0
    # tail mode: poll for appended bytes, emit complete lines only
    # (a torn trailing line stays buffered until its newline lands)
    pos = os.path.getsize(path) if os.path.exists(path) else 0
    buf = b""
    try:
        while True:
            time.sleep(0.5)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            if size < pos:  # journal rotated/truncated: restart
                pos, buf = 0, b""
            if size == pos:
                continue
            with open(path, "rb") as f:
                f.seek(pos)
                buf += f.read()
                pos = f.tell()
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if _match(rec):
                    _emit(rec)
    except KeyboardInterrupt:  # graftlint: ignore[swallow] — quiet ^C
        return 0


def _load_obs_checkpoint(session_dir: str) -> dict:
    """The durable-observability checkpoint straight off the dead
    cluster's journal (read-only replay; no compaction, no append)."""
    import pickle

    from ray_tpu._private.gcs_storage import Storage

    journal = os.path.join(session_dir, "gcs_journal.bin")
    if not os.path.exists(journal):
        return {}
    try:
        raw = Storage.open_readonly(journal).get("__obs", "checkpoint")
        return pickle.loads(raw) if raw else {}
    except Exception as e:  # noqa: BLE001 — a torn journal still leaves
        print(f"  <obs checkpoint unreadable: {e!r}>")  # bundles readable
        return {}


def _postmortem_report(session_dir: str) -> dict:
    """Assemble the cross-process incident report: crash bundles +
    persisted event journal + obs checkpoint, with per-node clock
    offsets applied so one timeline composes across processes."""
    from ray_tpu._private import blackbox

    bundles = blackbox.read_bundles(session_dir)
    events = blackbox.read_events_journal(session_dir)
    ckpt = _load_obs_checkpoint(session_dir)
    offsets = {str(k): float(v or 0.0)
               for k, v in (ckpt.get("clock_offsets") or {}).items()}

    timeline = []
    for e in events:
        t = e.get("timestamp") or 0.0
        timeline.append({"t": t, "source": e.get("source", "?"),
                         "severity": e.get("severity", "?"),
                         "what": e.get("message", ""), "event": e})
    for b in bundles:
        # bundle timestamps are the corpse's LOCAL clock: correct them
        # onto the GCS timebase before merging with journal events
        off = offsets.get(str(b.get("node_id") or ""), 0.0)
        timeline.append({
            "t": float(b.get("written_at") or 0.0) + off,
            "source": "blackbox", "severity": "ERROR",
            "what": (f"{b.get('role', '?')} pid {b.get('pid')} died "
                     f"({b.get('reason', '?')}"
                     f"{', ' + b['signal'] if b.get('signal') else ''}) — "
                     f"last flight data written here"),
            "bundle": b})
    timeline.sort(key=lambda r: r["t"])

    # SLO state at the end of the world (checkpointed alert state)
    slo_state = ((ckpt.get("slo") or {}).get("state")
                 or {}) if ckpt else {}
    alerts = [e for e in events
              if e.get("source") == "slo"
              or e.get("kind") in ("fast_burn", "slow_burn")]
    crashes = [e for e in events if e.get("kind") == "process_crash"]
    return {"session_dir": session_dir, "bundles": bundles,
            "events": events, "timeline": timeline, "alerts": alerts,
            "crash_events": crashes, "checkpoint": ckpt,
            "clock_offsets": offsets, "slo_state": slo_state}


def _perfetto_export(report: dict, path: str) -> int:
    """Chrome-trace (Perfetto) export of the incident timeline: one
    track per process (bundle deaths + their in-flight work as slices),
    journal events as instants on a 'cluster' track."""
    events = []
    for row in report["timeline"]:
        if "bundle" in row:
            b = row["bundle"]
            pid = int(b.get("pid") or 0)
            name = f"{b.get('role', 'proc')}-{pid}"
            events.append({
                "name": f"death: {b.get('reason', '?')}",
                "ph": "i", "s": "p", "pid": pid, "tid": 0,
                "ts": row["t"] * 1e6, "cat": "crash",
                "args": {"signal": b.get("signal", ""),
                         "bundled_by": b.get("bundled_by", "")}})
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": name}})
            for item in (b.get("inflight") or []):
                dur = float(item.get("age_s") or 0.0)
                events.append({
                    "name": (item.get("fn") or item.get("kind")
                             or "inflight"),
                    "ph": "X", "pid": pid, "tid": 1,
                    "ts": (row["t"] - dur) * 1e6, "dur": dur * 1e6,
                    "cat": "inflight",
                    "args": {k: v for k, v in item.items()
                             if isinstance(v, (str, int, float))}})
        else:
            events.append({
                "name": f"[{row['severity']}] {row['what'][:120]}",
                "ph": "i", "s": "g", "pid": 0, "tid": 0,
                "ts": row["t"] * 1e6, "cat": row["source"]})
    events.append({"name": "process_name", "ph": "M", "pid": 0,
                   "args": {"name": "cluster events"}})
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f, default=str)
    return len(events)


def cmd_postmortem(args) -> int:
    """Cross-process incident report for a dead (or dying) cluster:
    crash bundles, clock-corrected timeline, implicated in-flight work,
    last alerts, final stacks — assembled purely from session-dir files
    (<session>/blackbox/* + the GCS journal), no cluster required."""
    session_dir = _resolve_session_dir(args)
    report = _postmortem_report(session_dir)
    if args.json:
        print(json.dumps(report, default=str))
        return 0 if report["bundles"] else 1
    bundles = report["bundles"]
    print(f"postmortem: {session_dir}")
    print(f"crash bundles: {len(bundles)}")
    for b in bundles:
        age = ""
        if b.get("bundled_at") and b.get("written_at"):
            age = (f", flight data {b['bundled_at'] - b['written_at']:.1f}s"
                   f" old at sweep")
        print(f"  {b.get('role', '?'):7s} pid {b.get('pid')} on node "
              f"{str(b.get('node_id') or '?')[:12]}: "
              f"{b.get('reason', '?')}"
              f"{' sig ' + b['signal'] if b.get('signal') else ''}"
              f" (bundled by {b.get('bundled_by', '?')}{age})")
        inflight = b.get("inflight") or []
        if inflight:
            print(f"    in flight ({len(inflight)}):")
            for item in inflight[: args.top]:
                bits = [f"{k}={v}" for k, v in item.items()
                        if v not in (None, "") and k != "kind"]
                print(f"      {item.get('kind', '?'):10s} "
                      + "  ".join(bits))
        if args.stacks and b.get("stacks"):
            print("    final stacks:")
            for th in b["stacks"][: args.top]:
                if isinstance(th, dict):
                    print(f"      {th.get('name', '?')}: "
                          f"{th.get('stack', '')[-200:]}")
        logs = b.get("logs") or []
        if logs:
            print(f"    last log lines:")
            for line in logs[-3:]:
                print(f"      {line}")
    crashes = report["crash_events"]
    if crashes:
        print(f"crash events ({len(crashes)}):")
        for e in crashes[-args.top:]:
            print(f"  [{e.get('severity')}] {e.get('message')}")
    alerts = report["alerts"]
    if alerts:
        print(f"last alerts ({min(len(alerts), args.top)}):")
        for e in alerts[-args.top:]:
            extra = ""
            if e.get("artifacts"):
                extra = ("  artifacts: "
                         + ", ".join(sorted(e["artifacts"])))
            print(f"  [{e.get('severity')}] {e.get('message')}{extra}")
    slo_state = report["slo_state"]
    if slo_state:
        print("SLO state at last checkpoint:")
        for name, st in sorted(slo_state.items()):
            print(f"  {name}: alert={st.get('alert', '?')} "
                  f"({len(st.get('history') or [])} history samples)")
    n_timeline = len(report["timeline"])
    shown = report["timeline"][-args.timeline:]
    print(f"timeline (clock-corrected, last {len(shown)}/{n_timeline}):")
    for row in shown:
        ts = time.strftime("%H:%M:%S", time.localtime(row["t"]))
        print(f"  {ts} [{row['severity']:7s}] [{row['source']}] "
              f"{row['what']}")
    if args.perfetto:
        n = _perfetto_export(report, args.perfetto)
        print(f"wrote {n} trace events to {args.perfetto} "
              f"(open at https://ui.perfetto.dev)")
    return 0 if bundles else 1


def cmd_up(args) -> int:
    """ref: python/ray/scripts/scripts.py:1378 `up`."""
    from ..autoscaler.launcher import load_cluster_config, up

    out = up(load_cluster_config(args.config))
    print(f"cluster up: head {out['head']}, address {out['address']}, "
          f"{len(out['workers'])} worker(s)")
    print(f"connect with: ray_tpu.init(address={out['address']!r}) or "
          f"RAY_TPU_ADDRESS={out['address']}")
    return 0


def cmd_down(args) -> int:
    from ..autoscaler.launcher import down, load_cluster_config

    down(load_cluster_config(args.config))
    print("cluster down")
    return 0


def cmd_lint(args) -> int:
    """graftlint — concurrency- and error-plane-hazard static analysis
    (same entry point as ``python -m ray_tpu.devtools.graftlint``;
    ci.sh's lint phase)."""
    from ..devtools.graftlint.__main__ import main as lint_main

    argv = list(args.lint_args)
    if argv and argv[0] == "--":
        argv = argv[1:]
    return lint_main(argv)


# ------------------------------------------------------------------ main

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None,
                    help="existing cluster GCS (worker mode)")
    sp.add_argument("--port", type=int, default=None,
                    help="head GCS TCP port (default ephemeral)")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--object-store-memory", type=int, default=None)
    sp.add_argument("--node-ip", default=None)
    sp.add_argument("--block", action="store_true",
                    help="run in the foreground")
    sp.add_argument("--external-store", default=None,
                    help="address of a ray-tpu kv-server; the GCS "
                         "persists its tables there (head-disk loss "
                         "becomes survivable)")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("kv-server",
                        help="run the external GCS store (the Redis role)")
    sp.add_argument("--address", required=True,
                    help="unix socket path or host:port")
    sp.add_argument("--data", required=True,
                    help="directory for the persistent journal")
    sp.set_defaults(fn=cmd_kv_server)

    sp = sub.add_parser("stop", help="stop the node started on this host")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("up", help="launch a cluster from a config "
                                   "(the `ray up` role)")
    sp.add_argument("config", help="cluster YAML/JSON path")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a launched cluster")
    sp.add_argument("config", help="cluster YAML/JSON path")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("status", help="cluster nodes + resources")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("health",
                        help="stall sentinel: stalled tasks/transfers, "
                             "hung collectives, straggler scores")
    sp.add_argument("--address", default=None)
    sp.add_argument("--verbose", action="store_true",
                    help="print captured stacks inline")
    sp.add_argument("--events", type=int, default=20,
                    help="recent stall_sentinel events to show")
    sp.set_defaults(fn=cmd_health)

    sp = sub.add_parser("slo",
                        help="SLO plane: per-spec attainment, burn "
                             "rates, alert state + recent slo events")
    sp.add_argument("--address", default=None)
    sp.add_argument("--json", action="store_true",
                    help="dump the raw slo_status payload")
    sp.add_argument("--history", type=int, default=0,
                    help="show the last N attainment samples per spec")
    sp.add_argument("--events", type=int, default=20,
                    help="recent slo events to show")
    sp.set_defaults(fn=cmd_slo)

    sp = sub.add_parser("train",
                        help="training goodput: goodput %%, badput "
                             "breakdown, MFU, compile counts, rank skew")
    sp.add_argument("--address", default=None)
    sp.add_argument("--job", default=None,
                    help="filter to one experiment name")
    sp.add_argument("--json", action="store_true",
                    help="dump the raw train_status payload")
    sp.add_argument("--steps", type=int, default=0,
                    help="show the last N per-step breakdowns")
    sp.set_defaults(fn=cmd_train)

    sp = sub.add_parser("stacks",
                        help="live Python stacks of every worker "
                             "(cluster-wide py-spy dump)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--node", default=None,
                    help="node id hex prefix (default: all nodes)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_stacks)

    sp = sub.add_parser("profile",
                        help="cluster flamegraph: sample every worker's "
                             "stacks, merge folded stacks on the GCS")
    sp.add_argument("--address", default=None)
    sp.add_argument("--duration", type=float, default=5.0,
                    help="sampling window in seconds")
    sp.add_argument("--hz", type=float, default=100.0,
                    help="samples per second per worker")
    sp.add_argument("--node", default=None,
                    help="node id hex prefix (default: all nodes)")
    sp.add_argument("--deployment", default=None,
                    help="keep only stacks of tasks whose name "
                         "contains this string")
    sp.add_argument("--cpu", action="store_true",
                    help="CPU view (drop samples parked in waits)")
    sp.add_argument("--top", type=int, default=15,
                    help="folded stacks to print")
    sp.add_argument("--output", default=None,
                    help="write collapsed-stack text (flamegraph.pl)")
    sp.add_argument("--speedscope", default=None,
                    help="write speedscope JSON")
    sp.add_argument("--json", action="store_true",
                    help="dump the raw merged profile")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("job")
    sp.add_argument("--address", default=None)
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--submission-id", default=None)
    js.add_argument("--env-json", default=None,
                    help='runtime env, e.g. \'{"env_vars":{"A":"1"}}\'')
    js.add_argument("--follow", action="store_true",
                    help="stream logs until the job finishes")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run (prefix with --)")
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("submission_id")
    jsub.add_parser("list")
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("state")
    sp.add_argument("kind", choices=["nodes", "actors", "tasks", "objects"])
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_state)

    sp = sub.add_parser("timeline",
                        help="dump task events as chrome-trace JSON")
    sp.add_argument("--address", default=None)
    sp.add_argument("--output", default="/tmp/ray_tpu_timeline.json")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("summary",
                        help="critical-path report: wall time by "
                             "scheduling/dep-fetch/execution/transfer")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("memory",
                        help="memory attribution: store bytes by "
                             "ref-type, leak suspects, heap, HBM")
    sp.add_argument("--address", default=None)
    sp.add_argument("--leak-age", type=float, default=None,
                    help="age (s) after which a pinned unclaimed "
                         "object is a leak suspect")
    sp.add_argument("--top", type=int, default=20,
                    help="objects to include, largest first")
    sp.add_argument("--verbose", action="store_true",
                    help="print the per-object table")
    sp.add_argument("--json", action="store_true",
                    help="dump the raw memory report")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("events",
                        help="cluster events from the persisted journal "
                             "(works against a dead cluster); --follow "
                             "tails it")
    sp.add_argument("--session", default=None,
                    help="session dir (path or rtpu_* name; default: "
                         "most recent under /tmp/ray_tpu)")
    sp.add_argument("--severity", default=None,
                    choices=["INFO", "WARNING", "ERROR"],
                    help="only events at this severity")
    sp.add_argument("--source", default=None,
                    help="only events from this source (slo, blackbox, "
                         "NODE, stall_sentinel, ...)")
    sp.add_argument("--limit", type=int, default=200,
                    help="history lines to print before following")
    sp.add_argument("--follow", "-f", action="store_true",
                    help="keep tailing the journal (tail -f)")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("postmortem",
                        help="black-box incident report for a dead "
                             "cluster: crash bundles, clock-corrected "
                             "timeline, in-flight work, final stacks")
    sp.add_argument("--session", default=None,
                    help="session dir (path or rtpu_* name; default: "
                         "most recent under /tmp/ray_tpu)")
    sp.add_argument("--stacks", action="store_true",
                    help="print each corpse's final thread stacks")
    sp.add_argument("--top", type=int, default=8,
                    help="in-flight / alert rows per section")
    sp.add_argument("--timeline", type=int, default=25,
                    help="timeline rows to print")
    sp.add_argument("--perfetto", default=None,
                    help="write the incident timeline as chrome-trace "
                         "JSON (open at ui.perfetto.dev)")
    sp.add_argument("--json", action="store_true",
                    help="dump the raw report")
    sp.set_defaults(fn=cmd_postmortem)

    sp = sub.add_parser("lint",
                        help="graftlint: concurrency- and error-plane-"
                             "hazard static analysis (flags pass "
                             "through; see `ray-tpu lint -- --help`)")
    sp.add_argument("lint_args", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_lint)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "entrypoint", None):
        # strip the leading "--" REMAINDER keeps
        if args.entrypoint and args.entrypoint[0] == "--":
            args.entrypoint = args.entrypoint[1:]
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
