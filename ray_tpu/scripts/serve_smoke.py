"""Serve fleet-KV smoke lane (run by ci.sh): disaggregated
prefill/decode serving on the tiny model, end to end on a live
cluster. One prefill + one decode replica take shared-prefix traffic;
the round passes only if

 * the pooled deployment's tokens EXACTLY match a local monolithic
   engine with the same seed (handoff correctness, greedy oracle),
 * KV pages actually moved through the object store
   (serve_kv_handoff_bytes_total > 0, latency histogram populated),
 * the controller gossips prefix summaries for the deployment and
   `cli status` renders the serve section,
 * a spec-decode replica (adversarial drafter, llm/spec_decode.py)
   stays token-identical to the plain greedy oracle and its
   llm_spec_* counters reach a metrics scrape.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import ray_tpu
from ray_tpu import serve

_ECFG = {"max_num_seqs": 2, "max_seq_len": 128, "num_pages": 64,
         "page_size": 16, "enable_prefix_caching": True}


def _oracle_tokens(prompt, max_tokens: int):
    """Greedy tokens from a local monolithic engine, same seed the
    replicas use (LLMServer init='random', seed=0)."""
    import jax

    from ray_tpu.llm.engine import EngineConfig, LLMEngine
    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.models.llama import LLAMA_CONFIGS, init_params

    cfg = LLAMA_CONFIGS["tiny"]
    eng = LLMEngine(init_params(jax.random.PRNGKey(0), cfg), cfg,
                    EngineConfig(**_ECFG))
    eng.add_request(list(prompt),
                    SamplingParams(temperature=0.0, max_tokens=max_tokens))
    toks = []
    while eng.has_unfinished():
        for out in eng.step():
            toks.append(out.token)
    return toks


def _metric_total(name: str) -> float:
    from ray_tpu.util import state

    return sum(e.get("value", 0.0) for e in state.get_metrics(name))


def main() -> int:
    ray_tpu.init(num_cpus=4, _system_config={
        "serve_prefix_summary_interval_s": 0.5,
    })
    try:
        from ray_tpu.llm.serve import build_llm_deployment

        app = build_llm_deployment(
            "tiny", name="llm_smoke", pools={"prefill": 1, "decode": 1},
            engine_config=_ECFG)
        handle = serve.run(app)
        completions = handle.options(method_name="completions")

        prompt = list(range(1, 40))
        want = _oracle_tokens(prompt, 8)
        payload = {"prompt_ids": prompt, "temperature": 0.0,
                   "max_tokens": 8}

        # shared-prefix traffic: repeated prompts land on a decode
        # engine whose prefix cache the shipped pages already warmed
        for i in range(3):
            out = ray_tpu.get(completions.remote(dict(payload)),
                              timeout=300)
            got = out["choices"][0]["token_ids"]
            assert got == want, (
                f"pooled tokens diverge from monolithic oracle on "
                f"request {i}: {got} != {want}")

        deps = serve.status()
        dep = next(d for d in deps if d["name"] == "llm_smoke")
        assert dep.get("pools") == {"prefill": 1, "decode": 1}, dep

        # KV pages moved through the object store (the replica-side
        # metrics flusher is periodic: wait out one flush period)
        deadline = time.time() + 30
        moved = 0.0
        while time.time() < deadline:
            moved = _metric_total("serve_kv_handoff_bytes_total")
            if moved > 0:
                break
            time.sleep(0.5)
        assert moved > 0, "no KV handoff bytes recorded"
        assert _metric_total("serve_kv_handoff_retries_total") == 0

        # prefix summaries gossip within a few intervals
        deadline = time.time() + 20
        while time.time() < deadline:
            dep = next(d for d in serve.status()
                       if d["name"] == "llm_smoke")
            if dep.get("prefix_summaries", 0) > 0:
                break
            time.sleep(0.5)
        assert dep.get("prefix_summaries", 0) > 0, dep

        # `cli status` renders the serve section read-only
        from ray_tpu import _worker_api

        addr = _worker_api.node().gcs_address
        res = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.cli", "status",
             "--address", addr],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert res.returncode == 0, (res.returncode, res.stdout,
                                     res.stderr)
        assert "llm_smoke" in res.stdout, res.stdout
        assert "prefill=1" in res.stdout, res.stdout

        # --- speculative decoding leg (llm/spec_decode.py): the drafter
        # is initialized from a DIFFERENT seed than the target weights,
        # so most drafts reject — the strictest oracle gate: accept-
        # prefix emission must be token-identical to plain greedy decode
        # even when the drafter is wrong
        spec_app = build_llm_deployment(
            "tiny", name="llm_spec_smoke", engine_config=_ECFG,
            speculation={"draft_config": "tiny", "num_draft_tokens": 3,
                         "draft_seed": 1})
        spec_completions = serve.run(spec_app).options(
            method_name="completions")
        out = ray_tpu.get(spec_completions.remote(dict(payload)),
                          timeout=300)
        got = out["choices"][0]["token_ids"]
        assert got == want, (
            f"spec-decode tokens diverge from greedy oracle: "
            f"{got} != {want}")
        deadline = time.time() + 30
        drafted = 0.0
        while time.time() < deadline:
            drafted = _metric_total("llm_spec_draft_tokens_total")
            if drafted > 0:
                break
            time.sleep(0.5)
        assert drafted > 0, "no llm_spec_draft_tokens_total in any scrape"
        accepted = _metric_total("llm_spec_accepted_tokens_total")
        assert 0 <= accepted <= drafted, (accepted, drafted)

        print(f"serve smoke ok: {int(moved)} handoff bytes, "
              f"{dep['prefix_summaries']} prefix summaries, "
              f"spec {int(accepted)}/{int(drafted)} tokens accepted")
        serve.shutdown()
        return 0
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
