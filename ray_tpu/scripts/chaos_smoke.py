"""Chaos smoke lane (run by ci.sh, non-gating): boot a mini cluster,
trip randomized failpoints, and prove the error-plane invariant the
graftlint passes check statically — every injected fault surfaces as an
attributed error (or is absorbed by bounded retry), and NONE of them
becomes a hang the stall sentinel has to flag.

Each round draws from the entry table below, arms one failpoint spec
(programmatic arm(): the GCS, raylet, and object store all live in the
driver process), runs a small workload, asserts the expected outcome
(raise-faults carry the failpoint's site name; delay/drop-faults
complete through timeout+retry), then asserts stall-sentinel silence.

Repro: the chosen seed is printed; rerun with CHAOS_SEED=<n>.
"""

from __future__ import annotations

import os
import random
import sys
import time

import ray_tpu
from ray_tpu._private import failpoints
from ray_tpu.util import state


def _wait(pred, timeout_s: float, what: str):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


@ray_tpu.remote(num_cpus=0.5)  # sub-integer: force the full lease pipeline
def _double(x):
    return x * 2


def _expect_tasks_ok(n: int = 4) -> None:
    got = ray_tpu.get([_double.remote(i) for i in range(n)], timeout=60)
    assert got == [2 * i for i in range(n)], got


def _expect_raise(fn, site: str) -> None:
    try:
        fn()
    except BaseException as e:  # includes wrapped task errors
        text = f"{type(e).__name__}: {e}"
        assert site in text, (
            f"fault at {site} surfaced an UNattributed error: {text}")
        return
    raise AssertionError(f"fault at {site} surfaced no error at all")


# ---- round bodies -----------------------------------------------------

def round_lease_raise() -> None:
    """raise on lease grant: the submit pipeline must carry the error
    into the task's return objects — ray.get raises, attributed."""
    failpoints.arm("raylet.lease.grant=raise")
    _expect_raise(lambda: ray_tpu.get(_double.remote(1), timeout=60),
                  "raylet.lease.grant")


def round_seal_raise() -> None:
    """raise on object seal: put() of a non-inline object raises in the
    putting caller, store bookkeeping stays consistent for later puts."""
    failpoints.arm("object.seal=raise:0:1")
    _expect_raise(lambda: ray_tpu.put(b"x" * 200 * 1024), "object.seal")
    failpoints.disarm()
    ref = ray_tpu.put(b"y" * 200 * 1024)  # store usable after the fault
    assert ray_tpu.get(ref, timeout=30) == b"y" * 200 * 1024


def round_spill_raise() -> None:
    """raise on spill write: eviction-triggered spill I/O failure must
    propagate to the caller whose reservation forced the eviction."""
    failpoints.arm("spill.write=raise")
    refs = []

    def fill():
        for i in range(64):  # enough to overflow the shrunken store
            refs.append(ray_tpu.put(os.urandom(1024 * 1024)))
    _expect_raise(fill, "spill.write")


def round_dispatch_delay() -> None:
    """delay in RPC dispatch: straggler control-plane handlers; work
    completes and nothing stalls."""
    failpoints.arm("rpc.server.dispatch=delay:0.05:10")
    _expect_tasks_ok()
    assert failpoints.hit_counts().get("rpc.server.dispatch", 0) > 0, \
        "delay failpoint armed but never hit"


def round_heartbeat_delay() -> None:
    """delay in the raylet->GCS clock-sync ping: slow heartbeats must
    not wedge the raylet loop or flag anything."""
    failpoints.arm("raylet.heartbeat=delay:0.2:3")
    _wait(lambda: failpoints.hit_counts().get("raylet.heartbeat", 0) >= 1,
          15, "heartbeat failpoint to trip")
    _expect_tasks_ok()


def round_lease_send_drop() -> None:
    """drop the first two lease request frames: lease_rpc_timeout_s
    turns the loss into per-try timeouts and the retry (raylet dedups
    by request id) completes the task — loss, bounded, recovered."""
    failpoints.arm("rpc.client.send@request_worker_lease=drop:0:2")
    _expect_tasks_ok(n=1)
    assert failpoints.hit_counts().get(
        "rpc.client.send@request_worker_lease", 0) == 2, \
        failpoints.hit_counts()


def round_tail_hedge() -> None:
    """slow first copy of an idempotent task: the speculative hedge
    (not the sentinel, not a retry) erases the straggle — the task
    completes well under the injected latency, exactly one output
    seals, and the hedge counters land on the Prometheus scrape."""
    import tempfile

    from ray_tpu._private.config import global_config
    from ray_tpu._private.prometheus import render_cluster
    from ray_tpu.util.metrics import snapshot_local

    cfg = global_config()
    saved = {"task_speculation_enabled": cfg.task_speculation_enabled,
             "task_hedge_min_delay_s": cfg.task_hedge_min_delay_s,
             "task_hedge_ema_factor": cfg.task_hedge_ema_factor}
    cfg.apply_overrides({"task_speculation_enabled": True,
                         "task_hedge_min_delay_s": 0.2,
                         "task_hedge_ema_factor": 2.0})
    marker = tempfile.mktemp(prefix="chaos_tail_")
    try:
        @ray_tpu.remote(idempotent=True, num_cpus=0.5)
        def once_slow(marker, x):
            try:
                fd = os.open(marker, os.O_CREAT | os.O_EXCL)
                os.close(fd)
                time.sleep(3.0)  # the straggling first copy
            except FileExistsError:
                pass
            return x * 2

        # marker pre-claimed: fast runs warm the per-fn latency EMA so
        # the owner-side hedge delay is armed (not just watchdog hints)
        open(marker, "w").close()
        assert ray_tpu.get([once_slow.remote(marker, i)
                            for i in range(4)], timeout=60) == [0, 2, 4, 6]
        os.unlink(marker)

        before = snapshot_local("task_hedge")
        t0 = time.monotonic()
        assert ray_tpu.get(once_slow.remote(marker, 21), timeout=60) == 42
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0, (
            f"hedge never beat the 3s straggler ({elapsed:.1f}s)")
        after = snapshot_local("task_hedge")

        def delta(name):
            return after.get(name, 0) - before.get(name, 0)

        assert delta("task_hedges_launched") >= 1, after
        assert delta("task_hedges_won") >= 1, after
        assert delta("task_hedge_duplicate_publishes") == 0, after
        # counters reach the cluster scrape (2s flusher period)
        _wait(lambda: "task_hedges_launched" in render_cluster(),
              15, "hedge counters on the Prometheus scrape")
    finally:
        cfg.apply_overrides(saved)
        if os.path.exists(marker):
            os.unlink(marker)


ROUNDS = [
    ("lease-grant-raise", round_lease_raise),
    ("object-seal-raise", round_seal_raise),
    ("spill-write-raise", round_spill_raise),
    ("rpc-dispatch-delay", round_dispatch_delay),
    ("heartbeat-delay", round_heartbeat_delay),
    ("lease-send-drop", round_lease_send_drop),
    ("tail-hedge", round_tail_hedge),
]


def main() -> int:
    seed = int(os.environ.get("CHAOS_SEED", time.time_ns() % 100000))
    n_rounds = int(os.environ.get("CHAOS_ROUNDS", "3"))
    rng = random.Random(seed)
    chosen = rng.sample(ROUNDS, k=min(n_rounds, len(ROUNDS)))
    print(f"chaos smoke: seed={seed} rounds="
          f"{[name for name, _ in chosen]}", flush=True)

    ray_tpu.init(num_cpus=4, _system_config={
        # tight sentinel so a fault-turned-hang WOULD flag within the round
        "task_watchdog_interval_s": 0.5,
        "task_stall_threshold_s": 5.0,
        # frequent heartbeats so heartbeat-site rounds trip quickly
        "clock_sync_interval_s": 0.5,
        # small store so spill-site rounds reach eviction in a few puts
        "object_store_memory_bytes": 32 * 1024 * 1024,
        # dropped lease frames become per-try timeouts, not forever-waits
        "lease_rpc_timeout_s": 1.0,
    })
    try:
        for name, body in chosen:
            print(f"-- round: {name}", flush=True)
            try:
                body()
            finally:
                failpoints.disarm()
            # the invariant: injected faults surface as errors; the
            # sentinel (armed tight above) saw no hang to flag
            events = [e for e in state.list_cluster_events(
                source="stall_sentinel", severity="WARNING")]
            assert not events, (
                f"round {name}: injected fault became a stall: {events}")
            assert not state.list_stalls().get("tasks"), \
                f"round {name}: stalled tasks survived the round"
            _expect_tasks_ok(n=2)  # cluster still healthy post-fault
            print(f"   round {name}: ok", flush=True)
        print(f"chaos smoke ok ({len(chosen)} rounds, seed={seed})")
        return 0
    finally:
        failpoints.disarm()
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
