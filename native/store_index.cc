// Shared-memory object-store index: the native core of the node's
// object store (TPU-native analog of the plasma object table + eviction
// policy + capacity accounting; ref: src/ray/object_manager/plasma/
// object_store.h, eviction_policy.h, object_lifecycle_manager.h).
//
// One mmap'd index file per node, opened by every process. Slots form an
// open-addressed hash table keyed by 28-byte ObjectIDs; a process-shared
// ROBUST pthread mutex serializes mutations (a client dying mid-critical-
// section leaves the lock recoverable, not poisoned). Capacity accounting
// and LRU eviction therefore become node-global facts instead of the
// per-process approximations a pure-Python store is limited to. The data
// plane stays per-object tmpfs files (zero-copy mmap with inode-lifetime
// safety); this index is the authority on existence, size, seal state,
// pins and eviction order.
//
// Build: g++ -O2 -shared -fPIC -o libray_tpu_store.so store_index.cc -lpthread
// (driven by ray_tpu/_native/__init__.py).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include <cstdio>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055494E4458ULL;  // "RTPUINDX"
constexpr uint32_t kIdLen = 28;

enum SlotState : uint32_t {
  kEmpty = 0,
  kCreating = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct Slot {
  uint32_t state;
  uint32_t pin;
  uint64_t size;
  uint64_t last_access;  // logical clock tick, not wall time
  uint64_t ctime_ms;     // CLOCK_REALTIME ms at reservation: lets any
                         // process reclaim kCreating slots whose owner
                         // died mid-write (stale after kStaleCreatingMs)
  uint8_t id[kIdLen];
  uint8_t pad[4];
};

constexpr uint64_t kStaleCreatingMs = 60'000;

uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

struct Header {
  uint64_t magic;
  uint64_t capacity;
  uint64_t nslots;
  uint64_t used;
  uint64_t clock;
  uint64_t live;  // occupied (creating|sealed) slot count
  pthread_mutex_t mutex;
};

struct Index {
  Header* hdr;
  Slot* slots;
  size_t map_len;
  std::string data_dir;  // per-object data files live here (hex names);
                         // victims are unlinked UNDER the index mutex so
                         // an eviction cannot race a re-create's seal
  std::string spill_dir; // when set, sealed eviction victims are MOVED
                         // here instead of destroyed (ref: raylet/
                         // local_object_manager.h:45 spill-on-pressure;
                         // restore happens lazily on next access)
};

std::string hex_name(const uint8_t* id) {
  char name[kIdLen * 2 + 1];
  for (uint32_t i = 0; i < kIdLen; ++i)
    snprintf(name + 2 * i, 3, "%02x", id[i]);
  return std::string(name);
}

void unlink_data(const Index* ix, const uint8_t* id) {
  if (ix->data_dir.empty()) return;
  std::string path = ix->data_dir + "/" + hex_name(id);
  unlink(path.c_str());
}

// Move a victim's data file OUT OF THE STORE under the mutex — but
// never copy bytes while holding it: the file is renamed to a
// same-filesystem ".spilling" staging name (atomic, O(1)); the caller
// of rtpu_idx_reserve moves staged victims to the real (cross-fs) spill
// directory AFTER the lock is released. A 1 GB eviction must not stall
// every store operation on the node for the copy's duration.
void spill_data(const Index* ix, const uint8_t* id) {
  if (ix->data_dir.empty() || ix->spill_dir.empty()) {
    unlink_data(ix, id);
    return;
  }
  std::string name = hex_name(id);
  std::string src = ix->data_dir + "/" + name;
  std::string staged = ix->data_dir + "/" + name + ".spilling";
  if (rename(src.c_str(), staged.c_str()) != 0) unlink(src.c_str());
}

uint64_t hash_id(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;  // FNV-1a
  for (uint32_t i = 0; i < kIdLen; ++i) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// Robust lock: a holder that died leaves EOWNERDEAD; mark consistent and
// proceed — slot states are each updated atomically enough that the
// worst stale artifact is a kCreating slot whose owner is gone (aborted
// by later eviction pressure via idx_abort from the node manager).
int lock(Index* ix) {
  int rc = pthread_mutex_lock(&ix->hdr->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&ix->hdr->mutex);
    rc = 0;
  }
  return rc;
}

void unlock(Index* ix) { pthread_mutex_unlock(&ix->hdr->mutex); }

Slot* find(Index* ix, const uint8_t* id) {
  uint64_t n = ix->hdr->nslots;
  uint64_t i = hash_id(id) % n;
  for (uint64_t probes = 0; probes < n; ++probes) {
    Slot* s = &ix->slots[i];
    if (s->state == kEmpty) return nullptr;
    if (s->state != kTombstone && memcmp(s->id, id, kIdLen) == 0) return s;
    i = (i + 1) % n;
  }
  return nullptr;
}

Slot* find_insert(Index* ix, const uint8_t* id) {
  uint64_t n = ix->hdr->nslots;
  uint64_t i = hash_id(id) % n;
  Slot* grave = nullptr;
  for (uint64_t probes = 0; probes < n; ++probes) {
    Slot* s = &ix->slots[i];
    if (s->state == kEmpty) return grave ? grave : s;
    if (s->state == kTombstone) {
      if (!grave) grave = s;
    } else if (memcmp(s->id, id, kIdLen) == 0) {
      return s;  // existing entry
    }
    i = (i + 1) % n;
  }
  return grave;
}

void erase(Index* ix, Slot* s) {
  s->state = kTombstone;
  s->pin = 0;
  s->size = 0;
}

}  // namespace

extern "C" {

// Open (or create) the index file. For openers of an EXISTING file the
// geometry (capacity, nslots, mapping length) comes from the on-disk
// header — the caller's arguments only shape a fresh creation, so
// processes configured differently still agree on the creator's truth.
void* rtpu_idx_open(const char* path, uint64_t capacity, uint64_t nslots,
                    const char* data_dir) {
  size_t len = sizeof(Header) + sizeof(Slot) * nslots;
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  bool creator = fd >= 0;
  if (!creator) {
    if (errno != EEXIST) return nullptr;
    fd = open(path, O_RDWR);
    if (fd < 0) return nullptr;
    // the creator ftruncates to full length in one call before
    // publishing magic, so any nonzero size is the final size
    struct stat st;
    st.st_size = 0;
    for (int spin = 0;
         spin < 100000 && (fstat(fd, &st) != 0
                           || (size_t)st.st_size < sizeof(Header));
         ++spin)
      usleep(100);
    if ((size_t)st.st_size < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    len = (size_t)st.st_size;
  } else {
    if (ftruncate(fd, (off_t)len) != 0) {
      close(fd);
      unlink(path);
      return nullptr;
    }
  }
  void* mem =
      mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Index* ix = new Index{(Header*)mem, (Slot*)((char*)mem + sizeof(Header)),
                        len, data_dir ? std::string(data_dir) : std::string()};
  if (creator) {
    ix->hdr->capacity = capacity;
    ix->hdr->nslots = nslots;
    ix->hdr->used = 0;
    ix->hdr->clock = 1;
    ix->hdr->live = 0;
    pthread_mutexattr_t at;
    pthread_mutexattr_init(&at);
    pthread_mutexattr_setpshared(&at, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&at, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&ix->hdr->mutex, &at);
    pthread_mutexattr_destroy(&at);
    __sync_synchronize();
    ix->hdr->magic = kMagic;  // publish: openers spin on this
  } else {
    for (int spin = 0; spin < 100000 && ix->hdr->magic != kMagic; ++spin)
      usleep(100);
    if (ix->hdr->magic != kMagic
        || len < sizeof(Header) + sizeof(Slot) * ix->hdr->nslots) {
      munmap(mem, len);
      delete ix;
      return nullptr;
    }
  }
  return ix;
}

// Enable spill-on-eviction: sealed victims move here instead of dying.
void rtpu_idx_set_spill_dir(void* h, const char* dir) {
  Index* ix = (Index*)h;
  ix->spill_dir = dir ? std::string(dir) : std::string();
}

void rtpu_idx_close(void* h) {
  Index* ix = (Index*)h;
  munmap((void*)ix->hdr, ix->map_len);
  delete ix;
}

// Reserve capacity for a new object, evicting LRU sealed+unpinned
// entries if needed. Evicted ids are written into victims_out
// (max_victims * 28 bytes); *n_victims receives the count — the caller
// owns unlinking their data files. Returns:
//   0  reserved
//  -1  impossible (even evicting everything evictable won't fit)
//  -2  id already exists
//  -3  table full
int rtpu_idx_reserve(void* h, const uint8_t* id, uint64_t size,
                     uint8_t* victims_out, uint32_t max_victims,
                     uint32_t* n_victims) {
  Index* ix = (Index*)h;
  Header* hd = ix->hdr;
  *n_victims = 0;
  if (lock(ix) != 0) return -4;
  Slot* s = find_insert(ix, id);
  if (!s) {
    unlock(ix);
    return -3;
  }
  if (s->state == kCreating || s->state == kSealed) {
    unlock(ix);
    return -2;
  }
  if (hd->used + size > hd->capacity) {
    // plan the full eviction FIRST — a reservation that turns out
    // infeasible must not have destroyed anything. LRU order: collect
    // every sealed+unpinned slot, sort oldest-first, take a prefix.
    std::vector<Slot*> cands;
    cands.reserve(256);
    uint64_t now = now_ms();
    for (uint64_t i = 0; i < hd->nslots; ++i) {
      Slot* c = &ix->slots[i];
      if (c->state == kSealed && c->pin == 0) cands.push_back(c);
      // a creation whose owner died mid-write: reclaimable garbage.
      // now > ctime guard: a backward wall-clock step must not wrap
      // the unsigned diff and reclaim a LIVE in-progress creation
      else if (c->state == kCreating && now > c->ctime_ms
               && now - c->ctime_ms > kStaleCreatingMs)
        cands.push_back(c);
    }
    std::sort(cands.begin(), cands.end(), [](Slot* a, Slot* b) {
      // stale creations first (they hold no useful data), then LRU
      bool sa = a->state == kCreating, sb = b->state == kCreating;
      if (sa != sb) return sa;
      return a->last_access < b->last_access;
    });
    uint64_t reclaimed = 0;
    uint32_t count = 0;
    while (hd->used - reclaimed + size > hd->capacity) {
      if (count >= cands.size() || count >= max_victims) {
        unlock(ix);
        return -1;  // infeasible: index untouched
      }
      reclaimed += cands[count]->size;
      count++;
    }
    for (uint32_t j = 0; j < count; ++j) {
      memcpy(victims_out + (*n_victims) * kIdLen, cands[j]->id, kIdLen);
      (*n_victims)++;
      hd->used -= cands[j]->size;
      hd->live--;
      if (cands[j]->state == kSealed)
        spill_data(ix, cands[j]->id);   // under the mutex: no seal race
      else
        unlink_data(ix, cands[j]->id);  // stale creation: garbage
      erase(ix, cands[j]);
    }
  }
  s->state = kCreating;
  s->pin = 0;
  s->size = size;
  s->last_access = hd->clock++;
  s->ctime_ms = now_ms();
  memcpy(s->id, id, kIdLen);
  hd->used += size;
  hd->live++;
  unlock(ix);
  return 0;
}

int rtpu_idx_seal(void* h, const uint8_t* id) {
  Index* ix = (Index*)h;
  if (lock(ix) != 0) return -4;
  Slot* s = find(ix, id);
  int rc = 0;
  if (!s)
    rc = -1;
  else
    s->state = kSealed;
  unlock(ix);
  return rc;
}

int rtpu_idx_abort(void* h, const uint8_t* id) {
  Index* ix = (Index*)h;
  if (lock(ix) != 0) return -4;
  Slot* s = find(ix, id);
  if (s) {
    ix->hdr->used -= s->size;
    ix->hdr->live--;
    erase(ix, s);
  }
  unlock(ix);
  return s ? 0 : -1;
}

// Lookup. Returns 0 sealed (size filled), 1 absent, 2 still creating.
// ``touch`` != 0 refreshes LRU recency — existence probes (contains)
// pass 0 so polling cannot distort eviction order.
int rtpu_idx_lookup(void* h, const uint8_t* id, uint64_t* size_out,
                    int touch) {
  Index* ix = (Index*)h;
  if (lock(ix) != 0) return -4;
  Slot* s = find(ix, id);
  int rc;
  if (!s) {
    rc = 1;
  } else if (s->state == kCreating) {
    rc = 2;
  } else {
    *size_out = s->size;
    if (touch) s->last_access = ix->hdr->clock++;
    rc = 0;
  }
  unlock(ix);
  return rc;
}

int rtpu_idx_pin(void* h, const uint8_t* id, int delta) {
  Index* ix = (Index*)h;
  if (lock(ix) != 0) return -4;
  Slot* s = find(ix, id);
  int rc = -1;
  if (s) {
    if (delta > 0 || s->pin > 0) s->pin += delta;
    rc = 0;
  }
  unlock(ix);
  return rc;
}

int rtpu_idx_delete(void* h, const uint8_t* id) {
  Index* ix = (Index*)h;
  if (lock(ix) != 0) return -4;
  Slot* s = find(ix, id);
  if (s) {
    ix->hdr->used -= s->size;
    ix->hdr->live--;
    erase(ix, s);
  }
  unlock(ix);
  return s ? 0 : -1;
}

// Full memory fence for lock-free mmap protocols (channels publish a
// payload then a seq counter; weakly-ordered CPUs need a real barrier
// between the two stores, and between the reader's counter load and
// payload load).
void rtpu_fence(void) { __atomic_thread_fence(__ATOMIC_SEQ_CST); }

uint64_t rtpu_idx_used(void* h) { return ((Index*)h)->hdr->used; }
uint64_t rtpu_idx_live(void* h) { return ((Index*)h)->hdr->live; }
uint64_t rtpu_idx_capacity(void* h) { return ((Index*)h)->hdr->capacity; }

}  // extern "C"
