// Fast lane: shared-memory task-submission rings (the native task plane).
//
// TPU-native substitution for the reference's per-task gRPC hop
// (ref: src/ray/core_worker/transport/normal_task_submitter.h:227
// PushNormalTask, src/ray/rpc/grpc_server.h): once a worker lease is
// held, task frames stream driver->worker through a shared-memory byte
// ring with futex wakeups — no sockets, no event loop, no syscalls on
// the fast path beyond the futex when a side would block. The asyncio
// control plane still owns placement, failures and everything cold;
// this file is only the steady-state submission/reply data path (the
// same split plasma makes for objects: ref object_manager/plasma/).
//
// Layout of a ring file (mmap'd, lives in the session's store dir):
//   [Header][data bytes ...]
// Records are [u32 len][payload], wrapping byte-wise around the data
// area. head/tail are free-running u64 byte cursors (never wrapped);
// (head - tail) <= capacity is the invariant. Push/pop each take an
// in-header robust-ish spinlock only against their own side (multiple
// producers / multiple consumers each serialize; the two sides never
// share a lock). Cross-side visibility is seq-cst atomics + futex.
//
// Build: part of libray_tpu_core.so (see ray_tpu/_native/__init__.py).

#include <atomic>
#include <cerrno>
#include <new>
#include <sched.h>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <linux/futex.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x464C4E31;  // "FLN1"

struct Header {
  std::atomic<uint32_t> magic;  // release-published last by init,
                                // acquire-spun by open (cross-process)
  uint32_t capacity;                 // data area bytes
  std::atomic<uint64_t> head;        // bytes ever written
  std::atomic<uint64_t> tail;        // bytes ever consumed
  std::atomic<uint32_t> data_seq;    // bumped on push (futex word)
  std::atomic<uint32_t> space_seq;   // bumped on pop (futex word)
  std::atomic<uint32_t> closed;
  std::atomic<uint32_t> push_lock;   // producer-side mutex (spin+yield)
  std::atomic<uint32_t> pop_lock;    // consumer-side mutex
  uint32_t _pad[7];
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_len;
  int fd;
};

int futex_wait(std::atomic<uint32_t>* addr, uint32_t expect, int timeout_ms) {
  timespec ts, *tsp = nullptr;
  if (timeout_ms >= 0) {
    ts.tv_sec = timeout_ms / 1000;
    ts.tv_nsec = (timeout_ms % 1000) * 1000000L;
    tsp = &ts;
  }
  return syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT,
                 expect, tsp, nullptr, 0);
}

void futex_wake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE, INT32_MAX,
          nullptr, nullptr, 0);
}

void side_lock(std::atomic<uint32_t>& l) {
  // Same-side producers (or consumers) are nearly always uncontended;
  // spin briefly then yield. Not robust across holder death — a dying
  // holder means the owning process died and the lane is torn down.
  int spins = 0;
  uint32_t zero = 0;
  while (!l.compare_exchange_weak(zero, 1, std::memory_order_acquire)) {
    zero = 0;
    if (++spins > 256) {
      sched_yield();
      spins = 0;
    }
  }
}

void side_unlock(std::atomic<uint32_t>& l) {
  l.store(0, std::memory_order_release);
}

void copy_in(Ring* r, uint64_t at, const void* src, uint32_t n) {
  uint32_t cap = r->hdr->capacity;
  uint32_t off = static_cast<uint32_t>(at % cap);
  uint32_t first = n < cap - off ? n : cap - off;
  memcpy(r->data + off, src, first);
  if (n > first) memcpy(r->data, static_cast<const uint8_t*>(src) + first, n - first);
}

void copy_out(Ring* r, uint64_t at, void* dst, uint32_t n) {
  uint32_t cap = r->hdr->capacity;
  uint32_t off = static_cast<uint32_t>(at % cap);
  uint32_t first = n < cap - off ? n : cap - off;
  memcpy(dst, r->data + off, first);
  if (n > first) memcpy(static_cast<uint8_t*>(dst) + first, r->data, n - first);
}

int64_t now_ms() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000LL + ts.tv_nsec / 1000000LL;
}

}  // namespace

extern "C" {

// Create (truncate) a ring file with the given data capacity.
void* rtpu_ring_create(const char* path, uint32_t capacity) {
  size_t len = sizeof(Header) + capacity;
  int fd = open(path, O_CREAT | O_RDWR | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, len) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = new (mem) Header();
  h->capacity = capacity;
  h->head.store(0);
  h->tail.store(0);
  h->data_seq.store(0);
  h->space_seq.store(0);
  h->closed.store(0);
  h->push_lock.store(0);
  h->pop_lock.store(0);
  // release store publishes every prior header field; the opener's
  // acquire load pairs with it (a plain store + seq-cst fence leaves
  // the reader side unordered — formally a data race)
  h->magic.store(kMagic, std::memory_order_release);
  Ring* r = new Ring{h, static_cast<uint8_t*>(mem) + sizeof(Header), len, fd};
  return r;
}

// Open an existing ring; waits briefly for the creator to finish init.
void* rtpu_ring_open(const char* path) {
  int fd = -1;
  for (int i = 0; i < 200; i++) {  // creator may still be at ftruncate
    fd = open(path, O_RDWR);
    if (fd >= 0) {
      struct stat st;
      if (fstat(fd, &st) == 0 &&
          st.st_size >= static_cast<long>(sizeof(Header)))
        break;
      close(fd);
      fd = -1;
    }
    usleep(2000);
  }
  if (fd < 0) return nullptr;
  struct stat st;
  fstat(fd, &st);
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = static_cast<Header*>(mem);
  for (int i = 0;
       i < 500 && h->magic.load(std::memory_order_acquire) != kMagic; i++)
    usleep(1000);
  if (h->magic.load(std::memory_order_acquire) != kMagic) {
    munmap(mem, st.st_size);
    close(fd);
    return nullptr;
  }
  Ring* r = new Ring{h, static_cast<uint8_t*>(mem) + sizeof(Header),
                     static_cast<size_t>(st.st_size), fd};
  return r;
}

// Push one record. 0 ok; -1 closed; -2 timeout; -3 record larger than ring.
int rtpu_ring_push(void* rp, const void* buf, uint32_t len, int timeout_ms) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->hdr;
  uint32_t need = len + 4;
  if (need > h->capacity) return -3;
  int64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : -1;
  side_lock(h->push_lock);
  for (;;) {
    if (h->closed.load()) {
      side_unlock(h->push_lock);
      return -1;
    }
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    if (head + need - tail <= h->capacity) {
      copy_in(r, head, &len, 4);
      copy_in(r, head + 4, buf, len);
      h->head.store(head + need, std::memory_order_release);
      h->data_seq.fetch_add(1, std::memory_order_seq_cst);
      futex_wake(&h->data_seq);
      side_unlock(h->push_lock);
      return 0;
    }
    uint32_t seq = h->space_seq.load(std::memory_order_seq_cst);
    // re-check after loading the wait ticket (lost-wake race)
    tail = h->tail.load(std::memory_order_acquire);
    if (head + need - tail <= h->capacity) continue;
    int wait_ms = 50;
    if (deadline >= 0) {
      int64_t left = deadline - now_ms();
      if (left <= 0) {
        side_unlock(h->push_lock);
        return -2;
      }
      wait_ms = left < 50 ? static_cast<int>(left) : 50;
    }
    futex_wait(&h->space_seq, seq, wait_ms);
  }
}

// Pop one record into out (cap bytes). Returns payload length >= 0;
// -1 closed-and-drained; -2 timeout; -3 too small (*need_out set).
int64_t rtpu_ring_pop(void* rp, void* out, uint32_t cap, uint32_t* need_out,
                      int timeout_ms) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->hdr;
  int64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : -1;
  side_lock(h->pop_lock);
  for (;;) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint32_t len;
      copy_out(r, tail, &len, 4);
      if (len > cap) {
        if (need_out) *need_out = len;
        side_unlock(h->pop_lock);
        return -3;
      }
      copy_out(r, tail + 4, out, len);
      h->tail.store(tail + 4 + len, std::memory_order_release);
      h->space_seq.fetch_add(1, std::memory_order_seq_cst);
      futex_wake(&h->space_seq);
      side_unlock(h->pop_lock);
      return len;
    }
    if (h->closed.load()) {
      side_unlock(h->pop_lock);
      return -1;
    }
    uint32_t seq = h->data_seq.load(std::memory_order_seq_cst);
    head = h->head.load(std::memory_order_acquire);
    if (head != tail) continue;  // raced with a push
    int wait_ms = 50;
    if (deadline >= 0) {
      int64_t left = deadline - now_ms();
      if (left <= 0) {
        side_unlock(h->pop_lock);
        return -2;
      }
      wait_ms = left < 50 ? static_cast<int>(left) : 50;
    }
    futex_wait(&h->data_seq, seq, wait_ms);
  }
}

void rtpu_ring_close(void* rp) {
  Ring* r = static_cast<Ring*>(rp);
  r->hdr->closed.store(1);
  r->hdr->data_seq.fetch_add(1);
  r->hdr->space_seq.fetch_add(1);
  futex_wake(&r->hdr->data_seq);
  futex_wake(&r->hdr->space_seq);
}

int rtpu_ring_closed(void* rp) {
  return static_cast<Ring*>(rp)->hdr->closed.load() ? 1 : 0;
}

void rtpu_ring_free(void* rp) {
  Ring* r = static_cast<Ring*>(rp);
  munmap(r->hdr, r->map_len);
  close(r->fd);
  delete r;
}

}  // extern "C"
