// ThreadSanitizer stress harness over the native plane.
//
// The Python suites can't run under TSAN (uninstrumented CPython + the
// GIL drown it in noise), so race detection for the C++ components runs
// here: a standalone binary that hammers each engine from many threads
// and lets -fsanitize=thread adjudicate the interleavings. This is the
// .bazelrc tsan-config analog for this repo (SURVEY §5.2); ci.sh --tsan
// builds and runs it against all three translation units.
//
// Build (ci.sh does this):
//   g++ -std=c++17 -O1 -g -fsanitize=thread -pthread \
//       native/tsan_stress.cc native/store_index.cc \
//       native/core_tables.cc native/fastlane.cc -o /tmp/rtpu_tsan
//
// Exercised:
//   * store index   — concurrent reserve/seal/lookup/pin/delete over a
//                     shared mmap header (process-shared mutex path)
//   * refcount table— concurrent add/remove/pin/unpin on hot ids
//   * lease sched   — concurrent queue_push/pump/release
//   * shm rings     — two producer/consumer pairs across threads
//
// Exits 0 iff every invariant held; TSAN reports fail the lane.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
// store_index.cc
void* rtpu_idx_open(const char* path, uint64_t capacity, uint64_t nslots,
                    const char* data_dir);
void rtpu_idx_close(void* h);
int rtpu_idx_reserve(void* h, const uint8_t* id, uint64_t size,
                     uint8_t* victims_out, uint32_t max_victims,
                     uint32_t* n_victims);
int rtpu_idx_seal(void* h, const uint8_t* id);
int rtpu_idx_lookup(void* h, const uint8_t* id, uint64_t* size_out,
                    int touch);
int rtpu_idx_pin(void* h, const uint8_t* id, int delta);
int rtpu_idx_delete(void* h, const uint8_t* id);
uint64_t rtpu_idx_live(void* h);
// core_tables.cc
void* rtpu_rc_open();
void rtpu_rc_close(void* h);
void rtpu_rc_add_local(void* h, const uint8_t* id);
int rtpu_rc_remove_local(void* h, const uint8_t* id);
void rtpu_rc_pin_dep(void* h, const uint8_t* id);
int rtpu_rc_unpin_dep(void* h, const uint8_t* id);
int rtpu_rc_contains(void* h, const uint8_t* id);
uint64_t rtpu_rc_size(void* h);
void* rtpu_sched_open(uint64_t local_node);
void rtpu_sched_close(void* h);
void rtpu_sched_node_upsert(void* h, uint64_t node, const uint32_t* ids,
                            const double* tot, const double* avail,
                            uint32_t n);
void rtpu_sched_queue_push(void* h, uint64_t req_id, const uint32_t* ids,
                           const double* vals, uint32_t n, int32_t flags,
                           uint64_t affinity);
uint64_t rtpu_sched_pump(void* h, uint64_t* out_req, uint64_t* out_node,
                         uint64_t max);
void rtpu_sched_release(void* h, uint64_t node, const uint32_t* ids,
                        const double* vals, uint32_t n);
// fastlane.cc
void* rtpu_ring_create(const char* path, uint32_t capacity);
void* rtpu_ring_open(const char* path);
int rtpu_ring_push(void* rp, const void* buf, uint32_t len, int timeout_ms);
int64_t rtpu_ring_pop(void* rp, void* out, uint32_t cap, uint32_t* need_out,
                      int timeout_ms);
void rtpu_ring_close(void* rp);
}

namespace {

constexpr int kThreads = 4;
constexpr int kOpsPerThread = 2000;
std::atomic<int> failures{0};

void fill_id(uint8_t* id, int thread, int k) {
  std::memset(id, 0, 20);
  std::snprintf(reinterpret_cast<char*>(id), 20, "t%02d-%06d", thread, k);
}

void stress_index() {
  const char* path = "/dev/shm/rtpu_tsan_idx";
  std::remove(path);
  void* ix = rtpu_idx_open(path, 64 << 20, 1 << 12, nullptr);
  if (!ix) { failures++; return; }
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; t++) {
    ts.emplace_back([ix, t] {
      uint8_t id[20];
      uint8_t evicted[20 * 64];
      uint32_t n_evicted = 0;
      for (int k = 0; k < kOpsPerThread; k++) {
        fill_id(id, t, k % 97);
        switch (k % 5) {
          case 0:
            if (rtpu_idx_reserve(ix, id, 4096, evicted, 64,
                                 &n_evicted) == 0)
              rtpu_idx_seal(ix, id);
            break;
          case 1: {
            uint64_t size = 0;
            rtpu_idx_lookup(ix, id, &size, 1);
            break;
          }
          case 2:
            rtpu_idx_pin(ix, id, 1);
            rtpu_idx_pin(ix, id, -1);
            break;
          case 3:
            rtpu_idx_delete(ix, id);
            break;
          default: {
            uint64_t size = 0;
            rtpu_idx_lookup(ix, id, &size, 0);
          }
        }
      }
    });
  }
  for (auto& th : ts) th.join();
  rtpu_idx_close(ix);
  std::remove(path);
}

void stress_refcount() {
  void* rc = rtpu_rc_open();
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; t++) {
    ts.emplace_back([rc, t] {
      uint8_t id[20];
      for (int k = 0; k < kOpsPerThread; k++) {
        fill_id(id, t % 2, k % 31);  // two threads share each id range
        rtpu_rc_add_local(rc, id);
        rtpu_rc_pin_dep(rc, id);
        rtpu_rc_contains(rc, id);
        rtpu_rc_unpin_dep(rc, id);
        rtpu_rc_remove_local(rc, id);
      }
    });
  }
  for (auto& th : ts) th.join();
  rtpu_rc_close(rc);
}

void stress_sched() {
  void* s = rtpu_sched_open(1);
  uint32_t rid = 0;
  double cap = 1e9, amt = 1.0;
  rtpu_sched_node_upsert(s, 1, &rid, &cap, &cap, 1);
  std::atomic<uint64_t> next_req{1};
  std::vector<std::thread> ts;
  for (int t = 0; t < kThreads; t++) {
    ts.emplace_back([&, t] {
      uint32_t id0 = 0;
      double one = 1.0;
      uint64_t out_req[64], out_node[64];
      for (int k = 0; k < kOpsPerThread; k++) {
        if (t % 2 == 0) {
          rtpu_sched_queue_push(s, next_req.fetch_add(1), &id0, &one, 1,
                                0, 0);
        } else {
          uint64_t got = rtpu_sched_pump(s, out_req, out_node, 64);
          for (uint64_t i = 0; i < got; i++)
            rtpu_sched_release(s, out_node[i], &id0, &one, 1);
        }
      }
    });
  }
  for (auto& th : ts) th.join();
  rtpu_sched_close(s);
}

void stress_rings() {
  const char* base = "/dev/shm/rtpu_tsan_ring";
  std::remove(base);
  void* w = rtpu_ring_create(base, 1 << 16);
  void* r = rtpu_ring_open(base);
  if (!w || !r) { failures++; return; }
  std::atomic<long> sum_in{0}, sum_out{0};
  std::thread producer([&] {
    char buf[128];
    for (int k = 0; k < kOpsPerThread * 2; k++) {
      int len = 16 + (k % 100);
      std::memset(buf, k & 0xff, len);
      if (rtpu_ring_push(w, buf, len, 2000) != 0) { failures++; return; }
      sum_in += len;
    }
  });
  std::thread consumer([&] {
    char out[256];
    uint32_t need = 0;
    for (int k = 0; k < kOpsPerThread * 2; k++) {
      int64_t got = rtpu_ring_pop(r, out, sizeof(out), &need, 2000);
      if (got < 0) { failures++; return; }
      sum_out += got;
    }
  });
  producer.join();
  consumer.join();
  if (sum_in.load() != sum_out.load()) failures++;
  rtpu_ring_close(r);
  rtpu_ring_close(w);
  std::remove(base);
}

}  // namespace

int main() {
  stress_index();
  std::printf("index: live=%s ok\n", "done");
  stress_refcount();
  std::printf("refcount: ok\n");
  stress_sched();
  std::printf("sched: ok\n");
  stress_rings();
  std::printf("rings: ok\n");
  if (failures.load()) {
    std::printf("FAILURES: %d\n", failures.load());
    return 1;
  }
  std::printf("TSAN STRESS OK\n");
  return 0;
}
