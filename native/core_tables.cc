// Native core-worker/raylet hot-path tables.
//
// Two engines, both in-process (bound via ctypes from
// ray_tpu/_native/__init__.py, used by default with the pure-Python
// implementations kept as fallback):
//
//  1. Reference-count table — the ownership/GC hot path
//     (ref: src/ray/core_worker/reference_count.h:66). Every ObjectRef
//     clone/del and every task-arg pin crosses this table; keeping it
//     native removes dict+lock Python overhead from the per-object path
//     and gives O(1) free decisions.
//
//  2. Lease scheduler — the raylet's queue-and-dispatch loop
//     (ref: src/ray/raylet/scheduling/cluster_task_manager.h queueing +
//     policy/hybrid_scheduling_policy.h:50 local-first/top-k spillback,
//     over ResourceSet arithmetic from src/ray/common/scheduling/).
//     Resource names are interned to u32 ids Python-side; a ResourceSet
//     crosses the ABI as parallel (ids[], vals[]) arrays. The engine
//     owns node availability accounting and the FIFO pending queue and
//     answers "dispatch where?" for the whole backlog in one native
//     sweep — the BASELINE envelope (1M queued leases) never touches
//     Python per-entry.
//
// Keys are fixed-size 28-byte ids (matches ray_tpu/_private/ids.py).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kIdLen = 28;

struct IdKey {
  uint8_t b[kIdLen];
  bool operator==(const IdKey& o) const { return memcmp(b, o.b, kIdLen) == 0; }
};

struct IdHash {
  size_t operator()(const IdKey& k) const {
    // ids are already uniformly random (ref: id.h random bits) — fold.
    uint64_t a, c;
    uint32_t d;
    memcpy(&a, k.b, 8);
    memcpy(&c, k.b + 8, 8);
    memcpy(&d, k.b + 16, 4);
    return a ^ (c * 0x9e3779b97f4a7c15ULL) ^ d;
  }
};

// ---------------------------------------------------------------- refcount

struct RefEntry {
  int32_t local = 0;     // in-scope ObjectRef clones
  int32_t deps = 0;      // submitted-task argument pins
  uint8_t borrowed = 0;  // owned elsewhere: never free remotely
};

struct RefTable {
  std::mutex mu;
  std::unordered_map<IdKey, RefEntry, IdHash> map;
};

IdKey key_of(const uint8_t* id) {
  IdKey k;
  memcpy(k.b, id, kIdLen);
  return k;
}

// ---------------------------------------------------------------- scheduler

struct Vec {
  std::vector<uint32_t> ids;
  std::vector<double> vals;

  bool fits_in(const std::unordered_map<uint32_t, double>& avail) const {
    for (size_t i = 0; i < ids.size(); i++) {
      auto it = avail.find(ids[i]);
      double have = it == avail.end() ? 0.0 : it->second;
      if (have + 1e-9 < vals[i]) return false;
    }
    return true;
  }
};

struct Node {
  std::unordered_map<uint32_t, double> total;
  std::unordered_map<uint32_t, double> avail;
  bool alive = true;
};

struct PendingLease {
  uint64_t req_id;
  Vec req;
  int32_t flags;          // bit0: spread, bit1: no_spill (local only)
  uint64_t affinity_node; // nonzero: hard node affinity
  uint32_t skips = 0;     // sweeps this lease was passed over (aging)
};

struct Sched {
  std::mutex mu;
  std::unordered_map<uint64_t, Node> nodes;
  std::deque<PendingLease> queue;
  uint64_t local_node = 0;
  uint64_t rr = 0;  // round-robin cursor for spread/spill
};

void apply_sub(Node& n, const Vec& v) {
  for (size_t i = 0; i < v.ids.size(); i++) n.avail[v.ids[i]] -= v.vals[i];
}

void apply_add(Node& n, const Vec& v) {
  for (size_t i = 0; i < v.ids.size(); i++) {
    double& slot = n.avail[v.ids[i]];
    slot += v.vals[i];
    auto t = n.total.find(v.ids[i]);
    if (t != n.total.end() && slot > t->second) slot = t->second;  // drift clamp
  }
}

Vec make_vec(const uint32_t* ids, const double* vals, uint32_t n) {
  Vec v;
  v.ids.assign(ids, ids + n);
  v.vals.assign(vals, vals + n);
  return v;
}

}  // namespace

extern "C" {

// ---- refcount table ----

void* rtpu_rc_open() { return new RefTable(); }

void rtpu_rc_close(void* h) { delete static_cast<RefTable*>(h); }

void rtpu_rc_add_local(void* h, const uint8_t* id) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  t->map[key_of(id)].local++;
}

// Returns 1 when the object became unreferenced (caller frees), else 0.
int rtpu_rc_remove_local(void* h, const uint8_t* id) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  auto it = t->map.find(key_of(id));
  if (it == t->map.end()) return 0;
  if (--it->second.local <= 0 && it->second.deps <= 0) {
    int borrowed = it->second.borrowed;
    t->map.erase(it);
    return borrowed ? 2 : 1;  // 2: drop local state only, owner frees
  }
  return 0;
}

void rtpu_rc_pin_dep(void* h, const uint8_t* id) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  t->map[key_of(id)].deps++;
}

int rtpu_rc_unpin_dep(void* h, const uint8_t* id) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  auto it = t->map.find(key_of(id));
  if (it == t->map.end()) return 0;
  if (--it->second.deps <= 0 && it->second.local <= 0) {
    int borrowed = it->second.borrowed;
    t->map.erase(it);
    return borrowed ? 2 : 1;
  }
  return 0;
}

void rtpu_rc_set_borrowed(void* h, const uint8_t* id) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  RefEntry& e = t->map[key_of(id)];
  e.borrowed = 1;
  e.local++;
}

int rtpu_rc_contains(void* h, const uint8_t* id) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return t->map.count(key_of(id)) ? 1 : 0;
}

uint64_t rtpu_rc_size(void* h) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  return t->map.size();
}

// local refcount of an id (0 if absent) — observability/state API.
int rtpu_rc_local_count(void* h, const uint8_t* id) {
  RefTable* t = static_cast<RefTable*>(h);
  std::lock_guard<std::mutex> g(t->mu);
  auto it = t->map.find(key_of(id));
  return it == t->map.end() ? 0 : it->second.local;
}

// ---- lease scheduler ----

void* rtpu_sched_open(uint64_t local_node) {
  Sched* s = new Sched();
  s->local_node = local_node;
  return s;
}

void rtpu_sched_close(void* h) { delete static_cast<Sched*>(h); }

void rtpu_sched_node_upsert(void* h, uint64_t node, const uint32_t* ids,
                            const double* tot, const double* avail,
                            uint32_t n) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  Node& nd = s->nodes[node];
  nd.alive = true;
  for (uint32_t i = 0; i < n; i++) {
    nd.total[ids[i]] = tot[i];
    nd.avail[ids[i]] = avail[i];
  }
}

void rtpu_sched_node_remove(void* h, uint64_t node) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->nodes.erase(node);
}

// Direct allocation attempt on one node (the grant path). 1 = allocated.
int rtpu_sched_try_allocate(void* h, uint64_t node, const uint32_t* ids,
                            const double* vals, uint32_t n) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end()) return 0;
  Vec v = make_vec(ids, vals, n);
  if (!v.fits_in(it->second.avail)) return 0;
  apply_sub(it->second, v);
  return 1;
}

void rtpu_sched_release(void* h, uint64_t node, const uint32_t* ids,
                        const double* vals, uint32_t n) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end()) return;
  apply_add(it->second, make_vec(ids, vals, n));
}

void rtpu_sched_queue_push(void* h, uint64_t req_id, const uint32_t* ids,
                           const double* vals, uint32_t n, int32_t flags,
                           uint64_t affinity_node) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->queue.push_back({req_id, make_vec(ids, vals, n), flags, affinity_node});
}

int rtpu_sched_queue_remove(void* h, uint64_t req_id) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  for (auto it = s->queue.begin(); it != s->queue.end(); ++it) {
    if (it->req_id == req_id) {
      s->queue.erase(it);
      return 1;
    }
  }
  return 0;
}

uint64_t rtpu_sched_pending(void* h) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->queue.size();
}

// Sweep the pending queue once, granting every dispatchable lease.
// Hybrid policy (ref: hybrid_scheduling_policy.h:50): local node first
// unless SPREAD, else round-robin over fitting remotes (spillback);
// hard affinity pins to one node. Resources are debited here. Writes up
// to `max` (req_id, node) pairs; returns the count.
//
// Ordering: FIFO with per-sweep skip of non-fitting leases — a
// non-fitting request does NOT block differently shaped requests
// behind it. To keep a large lease from being starved forever by a
// stream of smaller later arrivals, a lease skipped kAgingSweeps
// times becomes a barrier: once it fails to place, the sweep stops
// granting, so freed capacity accumulates for the oldest starved
// lease instead of being re-consumed by newer small ones. A lease
// that can NEVER place (dead affinity node, req bigger than any
// node's total) must not become a forever-barrier, so the barrier
// only arms for leases feasible against some node's TOTAL capacity.
constexpr uint32_t kAgingSweeps = 64;

uint64_t rtpu_sched_pump(void* h, uint64_t* out_req, uint64_t* out_node,
                         uint64_t max) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  uint64_t granted = 0;
  std::deque<PendingLease> keep;
  bool barrier = false;
  auto feasible = [&](const PendingLease& p) {
    if (p.affinity_node != 0) {
      auto it = s->nodes.find(p.affinity_node);
      return it != s->nodes.end() && it->second.alive &&
             p.req.fits_in(it->second.total);
    }
    bool no_spill = p.flags & 2;
    for (auto& kv : s->nodes) {
      if (!kv.second.alive) continue;
      if (no_spill && kv.first != s->local_node) continue;
      if (p.req.fits_in(kv.second.total)) return true;
    }
    return false;
  };
  while (!s->queue.empty() && granted < max) {
    if (barrier) {
      keep.push_back(std::move(s->queue.front()));
      s->queue.pop_front();
      continue;
    }
    PendingLease p = std::move(s->queue.front());
    s->queue.pop_front();
    uint64_t chosen = 0;
    if (p.affinity_node != 0) {
      auto it = s->nodes.find(p.affinity_node);
      if (it != s->nodes.end() && it->second.alive &&
          p.req.fits_in(it->second.avail))
        chosen = p.affinity_node;
    } else {
      bool spread = p.flags & 1;
      bool no_spill = p.flags & 2;
      auto local = s->nodes.find(s->local_node);
      if (!spread && local != s->nodes.end() &&
          p.req.fits_in(local->second.avail)) {
        chosen = s->local_node;
      } else if (!no_spill || spread) {
        // deterministic rotation over nodes (map order is stable enough
        // within a sweep; rr makes successive grants fan out)
        std::vector<uint64_t> fitting;
        for (auto& kv : s->nodes) {
          if (!kv.second.alive) continue;
          if (no_spill && kv.first != s->local_node) continue;
          if (p.req.fits_in(kv.second.avail)) fitting.push_back(kv.first);
        }
        if (!fitting.empty()) chosen = fitting[s->rr++ % fitting.size()];
      } else if (local != s->nodes.end() &&
                 p.req.fits_in(local->second.avail)) {
        chosen = s->local_node;
      }
    }
    if (chosen != 0) {
      apply_sub(s->nodes[chosen], p.req);
      out_req[granted] = p.req_id;
      out_node[granted] = chosen;
      granted++;
    } else {
      p.skips++;
      if (p.skips >= kAgingSweeps && feasible(p)) barrier = true;
      keep.push_back(std::move(p));
    }
  }
  // preserve FIFO order of the still-pending tail
  while (!keep.empty()) {
    s->queue.push_front(std::move(keep.back()));
    keep.pop_back();
  }
  return granted;
}

double rtpu_sched_avail(void* h, uint64_t node, uint32_t res_id) {
  Sched* s = static_cast<Sched*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->nodes.find(node);
  if (it == s->nodes.end()) return 0.0;
  auto r = it->second.avail.find(res_id);
  return r == it->second.avail.end() ? 0.0 : r->second;
}

}  // extern "C"
