#!/usr/bin/env bash
# CI entry point (ref: the reference's ci/ + root pytest.ini contract):
#   1. graftlint: concurrency- and error-plane-hazard static analysis,
#      gated on the checked-in baseline (graftlint_baseline.json)
#   2. native build must succeed from scratch (content-hash cache bypassed)
#   3. full test suite on the virtual 8-device CPU mesh, per-test timeout
#   4. multichip dry-run (the driver's own validation, run here too)
# One wedged test cannot hang the round: tests/conftest.py arms a
# per-test SIGALRM (RAY_TPU_TEST_TIMEOUT_S, default 180 s) and this
# script bounds each phase with a hard wall clock.
set -euo pipefail
cd "$(dirname "$0")"

# --sanitize: ASAN pass over the native plane (ref: .bazelrc asan
# configs). Rebuilds the C++ libs instrumented and runs the
# native-heavy suites under libasan. Run BEFORE the normal suite so a
# corrupted cache dir never leaks into it.
# --tsan: ThreadSanitizer over the native plane via a standalone C++
# stress harness (native/tsan_stress.cc) — CPython can't run under TSAN
# (uninstrumented interpreter + GIL noise), so the C++ engines get their
# race-detection lane in pure C++ (the .bazelrc tsan-config analog).
if [[ "${1:-}" == "--tsan" ]]; then
    echo "== TSAN: native stress harness =="
    g++ -std=c++17 -O1 -g -fsanitize=thread -pthread \
        native/tsan_stress.cc native/store_index.cc \
        native/core_tables.cc native/fastlane.cc -o /tmp/rtpu_tsan
    TSAN_OPTIONS="halt_on_error=1" timeout 600 /tmp/rtpu_tsan
    echo "TSAN PASSED"
    exit 0
fi

if [[ "${1:-}" == "--sanitize" ]]; then
    echo "== ASAN: native rebuild + native-plane suites =="
    rm -rf ray_tpu/_native/build
    LIBASAN="$(g++ -print-file-name=libasan.so)"
    # the instrumented lib must actually LOAD under the preload —
    # otherwise get_lib()'s graceful Python fallback would let the
    # whole lane "pass" with zero native coverage
    RAY_TPU_NATIVE_SANITIZE=address \
    LD_PRELOAD="$LIBASAN" \
    ASAN_OPTIONS="detect_leaks=0" \
    python - <<'PY'
from ray_tpu._native import get_lib, native_unavailable_reason
assert get_lib() is not None, \
    f"ASAN-instrumented native lib failed to load: {native_unavailable_reason()}"
print("instrumented native lib loaded")
PY
    # test_tensor_lane_asan.py drives the raw-tensor ring with numpy/
    # ml_dtypes only, so the native tensor path gets sanitizer coverage;
    # -k "not tensor and not device" still excludes the jax-INITIALIZING
    # tensor/DeviceChannel tests (uninstrumented jaxlib crashes under
    # the libasan preload once a backend comes up)
    RAY_TPU_NATIVE_SANITIZE=address \
    LD_PRELOAD="$LIBASAN" \
    ASAN_OPTIONS="detect_leaks=0" \
    JAX_PLATFORMS=cpu \
    timeout "${CI_ASAN_TIMEOUT_S:-1200}" \
        python -m pytest tests/test_native_store.py tests/test_fastlane.py \
            tests/test_dag.py tests/test_tensor_lane_asan.py \
            -q -k "(not tensor and not device) or tensor_lane_asan"
    rm -rf ray_tpu/_native/build   # drop instrumented builds
    echo "ASAN PASSED"
    exit 0
fi

echo "== [1/9] graftlint: concurrency + error-plane static analysis =="
# gating: findings not in the checked-in baseline fail the round — fix
# the hazard, suppress inline (# graftlint: ignore[pass]) with a
# justification, or deliberately accept it via
#   python -m ray_tpu.devtools.graftlint --update-baseline
JAX_PLATFORMS=cpu timeout "${CI_LINT_TIMEOUT_S:-120}" \
    python -m ray_tpu.devtools.graftlint --baseline graftlint_baseline.json

echo "== [2/9] native build =="
rm -rf ray_tpu/_native/build
python - <<'PY'
from ray_tpu._native import get_lib, native_unavailable_reason
assert get_lib() is not None, native_unavailable_reason()
print("native lib built + loaded")
PY

echo "== [3/9] data-plane smoke: transfer + spilling + shuffle =="
# the bulk data plane (cut-through relay watermark, parallel spill I/O,
# push-based shuffle exchange) gets its own early, explicit lane: a
# broken transfer/spill/shuffle path fails the round in minutes instead
# of surfacing mid-suite
JAX_PLATFORMS=cpu \
RAY_TPU_TEST_TIMEOUT_S="${RAY_TPU_TEST_TIMEOUT_S:-180}" \
timeout "${CI_SMOKE_TIMEOUT_S:-600}" \
    python -m pytest tests/test_object_transfer.py tests/test_spilling.py \
        tests/test_data_shuffle.py -q

echo "== [4/9] observability smoke: lifecycle + timeline + serve metrics + stall sentinel + profiling + slo + train goodput + postmortem =="
# the flight recorder (task state transitions, Perfetto export, serving
# histograms) gets a live end-to-end check: a silent telemetry
# regression would otherwise only show up as weaker dashboards, not a
# test failure. The stall-injection leg hangs a task on purpose and
# requires the sentinel to flag it (WARNING event + captured stack)
# through `cli health` and `cli stacks` with no human action. The
# profiling leg requires `cli profile` to name a known-hot function in
# the merged cluster flamegraph and `cli memory` to flag a deliberately
# pinned ownerless object as a leak suspect. The slo
# leg installs specs at runtime, requires per-tenant attainment from
# live traffic, and injects a slow replica that must fire the fast
# burn-rate ERROR alert. The train leg runs a short sharded fit on the
# tiny config and requires the GCS goodput ledger to attribute the
# chip-seconds (goodput < 1.0, nonzero compile badput), `cli train` to
# render the breakdown, and train_step_seconds to reach the Prometheus
# scrape. The postmortem leg kill -9s a worker mid-task
# under background load: the raylet must sweep the corpse's flight file
# into a crash bundle and `cli postmortem` must name the dead pid and
# the in-flight task id from files alone — every wait is
# deadline-bounded (never a hang)
JAX_PLATFORMS=cpu \
timeout "${CI_OBS_TIMEOUT_S:-480}" \
    python -m ray_tpu.scripts.obs_smoke

echo "== [5/9] serve smoke: disaggregated prefill/decode + fleet KV routing + spec decode =="
# the fleet KV plane gets its own live lane: 1 prefill + 1 decode
# replica on the tiny model, shared-prefix traffic — tokens must match
# a local monolithic engine exactly, KV pages must move through the
# object store, and prefix summaries must gossip to the controller;
# a spec-decode replica (adversarial drafter) must stay token-identical
# to the plain greedy oracle with llm_spec_* counters on the scrape
JAX_PLATFORMS=cpu \
timeout "${CI_SERVE_TIMEOUT_S:-600}" \
    python -m ray_tpu.scripts.serve_smoke

echo "== [6/9] chaos smoke: failpoint fault injection (non-gating) =="
# randomized failpoint rounds (ray_tpu/scripts/chaos_smoke.py): every
# injected fault — raised, delayed, or dropped at the RPC/lease/seal/
# spill/heartbeat seams — must surface as an attributed error with the
# stall sentinel silent, never a hang. Non-gating while the fault
# corpus grows: a failure prints the reproducing CHAOS_SEED and moves
# on — re-run it locally with that seed and triage before merging.
if ! JAX_PLATFORMS=cpu \
        timeout "${CI_CHAOS_TIMEOUT_S:-420}" \
        python -m ray_tpu.scripts.chaos_smoke; then
    echo "WARNING: chaos smoke failed (non-gating) — rerun with the" \
        "printed CHAOS_SEED and triage before merging"
fi

echo "== [7/9] TSAN stress over the native plane (non-gating) =="
# the --tsan lane, folded into every round as advisory signal: races it
# finds are real, but sanitizer availability varies across builders, so
# this leg never fails the round — it prints loudly and moves on.
if echo 'int main(){return 0;}' | \
        g++ -fsanitize=thread -pthread -x c++ - \
        -o /tmp/rtpu_tsan_probe 2>/dev/null && /tmp/rtpu_tsan_probe; then
    ./ci.sh --tsan || echo "WARNING: TSAN stress failed (non-gating) —" \
        "run ./ci.sh --tsan locally and triage before merging"
else
    echo "toolchain lacks a working -fsanitize=thread; skipping"
fi

echo "== [8/9] test suite =="
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
JAX_PLATFORMS=cpu \
RAY_TPU_TEST_TIMEOUT_S="${RAY_TPU_TEST_TIMEOUT_S:-180}" \
timeout "${CI_SUITE_TIMEOUT_S:-3000}" \
    python -m pytest tests/ -q

echo "== [9/9] multichip dry-run =="
timeout "${CI_DRYRUN_TIMEOUT_S:-1200}" \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

echo "CI PASSED"
