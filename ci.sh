#!/usr/bin/env bash
# CI entry point (ref: the reference's ci/ + root pytest.ini contract):
#   1. native build must succeed from scratch (content-hash cache bypassed)
#   2. full test suite on the virtual 8-device CPU mesh, per-test timeout
#   3. multichip dry-run (the driver's own validation, run here too)
# One wedged test cannot hang the round: tests/conftest.py arms a
# per-test SIGALRM (RAY_TPU_TEST_TIMEOUT_S, default 180 s) and this
# script bounds each phase with a hard wall clock.
set -euo pipefail
cd "$(dirname "$0")"

echo "== [1/3] native build =="
rm -rf ray_tpu/_native/build
python - <<'PY'
from ray_tpu._native import get_lib, native_unavailable_reason
assert get_lib() is not None, native_unavailable_reason()
print("native lib built + loaded")
PY

echo "== [2/3] test suite =="
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
JAX_PLATFORMS=cpu \
RAY_TPU_TEST_TIMEOUT_S="${RAY_TPU_TEST_TIMEOUT_S:-180}" \
timeout "${CI_SUITE_TIMEOUT_S:-3000}" \
    python -m pytest tests/ -q

echo "== [3/3] multichip dry-run =="
timeout "${CI_DRYRUN_TIMEOUT_S:-1200}" \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

echo "CI PASSED"
